"""Local execution: lower a logical plan to streaming page pipelines.

Reference parity: sql/planner/LocalExecutionPlanner.java:420 — each plan node
maps to an operator implementation over Pages (visitTableScan:1733,
visitFilter/visitProject via ScanFilterAndProject:1606, visitAggregation:1534,
visitJoin:2109, visitTopN, visitSort, visitLimit, visitSemiJoin, ...).

Execution model (Driver.java replacement): a node executes to an iterator of
fixed-capacity Pages plus a symbol layout. Device work per page runs under
jit — traces cache on (capacity, dtypes), so steady-state streaming is one
compiled XLA call per page per pipeline stage. Blocking operators (agg, sort,
join build) consume their input eagerly, as their Java counterparts do across
addInput/finish.

Dynamic row counts under static shapes (SURVEY §7 hard part 1): operators
carry a true-total scalar; when an output overflows its static capacity the
executor doubles the capacity bucket and re-runs (hash_join contract).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.connector.spi import Split
from trino_tpu.errors import GENERIC_INTERNAL_ERROR, TrinoError
from trino_tpu.exec.jit_cache import cached_kernel
from trino_tpu.expr.compiler import compile_expression, compile_filter
from trino_tpu.expr.ir import (Call, InputRef, Literal, RowExpression,
                               SpecialForm, SpecialKind, SymbolRef)
from trino_tpu.metadata import Metadata, Session
from trino_tpu.ops import (AggSpec, JoinType, SortKey, Step, hash_aggregate,
                           hash_join, order_by, prepare_build, top_n,
                           top_n_masked)
from trino_tpu.ops.join import unique_inner_probe
from trino_tpu.page import Column, Page, concat_pages
from trino_tpu.planner.nodes import (
    AggregationNode, AggStep, DistinctLimitNode, EnforceSingleRowNode,
    ExchangeNode, FilterNode, GroupIdNode, JoinClause, JoinKind, JoinNode,
    LimitNode, OffsetNode, OutputNode, PlanNode, ProjectNode, SemiJoinNode,
    SortNode, Symbol, TableScanNode, TopNNode, UnionNode, ValuesNode,
    WindowNode, TableWriterNode)


class ExecutionError(TrinoError):
    """Operator-lowering/runtime defect: internal, not retryable (the
    same plan re-fails identically)."""

    CODE = GENERIC_INTERNAL_ERROR


def lower_expr(e: RowExpression, layout: Dict[str, int],
               types: Dict[str, T.Type]) -> RowExpression:
    """SymbolRef -> InputRef against a page layout (the compiled-PageProcessor
    channel mapping step)."""
    if isinstance(e, SymbolRef):
        if e.name not in layout:
            raise ExecutionError(f"symbol {e.name} not in layout")
        return InputRef(layout[e.name], types[e.name])
    if isinstance(e, Call):
        return Call(e.name, tuple(lower_expr(a, layout, types)
                                  for a in e.args), e.type)
    if isinstance(e, SpecialForm):
        return SpecialForm(e.kind, tuple(lower_expr(a, layout, types)
                                         for a in e.args), e.type)
    return e


def _layout(symbols: Sequence[Symbol]) -> Tuple[Dict[str, int],
                                                Dict[str, T.Type]]:
    lay = {s.name: i for i, s in enumerate(symbols)}
    typ = {s.name: s.type for s in symbols}
    return lay, typ


def _next_pow2(n: int) -> int:
    out = 1024
    while out < n:
        out *= 2
    return out


@dataclasses.dataclass
class PageStream:
    """Stream of pages + a lazy chain of per-page device transforms.

    WorkProcessor-style (operator/WorkProcessor.java:31): streaming operators
    (filter/project/column-select) don't dispatch device work themselves —
    they append (cache_key, kernel_builder, params) entries to `pending`.
    Consumers drain via iter_pages(), which compiles ONE composed kernel for
    the whole chain (cached), so a scan->filter->project pipeline is a single
    XLA program per page, and blocking operators can fuse the chain into
    their own kernel (ScanFilterAndProjectOperator's fusion, compile-once).

    `params` per entry is the op's hoisted-literal tuple (expr/hoist.py):
    keys carry the literal-free canonical expression, and the values flow
    into the composed kernel as traced scalar operands — so every literal
    variant of a chain shape shares one XLA executable. Builders therefore
    return fn(page, params), with params=() for literal-free ops.

    Operator attribution (round 13): under operator-level stats
    collection each entry may carry a FOURTH element — the owning plan
    node's OperatorStats slot. The slot never enters the chain cache key
    (canonical keys stay literal- and query-free), and it never splits
    the chain: compose_chain times the fused dispatch once and
    apportions the measured wall across the tagged entries by XLA cost
    analysis (obs/profiler.py). Entries without a slot are plain
    3-tuples, so the untagged fast path is byte-identical to before.
    """

    pages: Iterator[Page]
    symbols: Tuple[Symbol, ...]
    pending: Tuple[tuple, ...] = ()

    def with_op(self, key, builder, params=()) -> "PageStream":
        return PageStream(self.pages, self.symbols,
                          self.pending + ((key, builder, tuple(params)),))

    def iter_pages(self) -> Iterator[Page]:
        fn = compose_chain(self.pending)
        if fn is None:
            yield from self.pages
        else:
            for p in self.pages:
                yield fn(p)


def chain_keys(pending) -> Tuple:
    return tuple(e[0] for e in pending)


def chain_params(pending) -> Tuple:
    """Per-op runtime literal tuples, positionally aligned with
    chain_keys — the traced argument the composed kernel receives."""
    return tuple(tuple(e[2]) for e in pending)


def compose_chain(pending, tail_key=None, tail_builder=None,
                  tail_slot=None):
    """One cached jitted kernel running every pending transform (+ optional
    tail op, e.g. a partial aggregation) in a single device program. The
    cache key holds only canonical (literal-free) op keys; hoisted literal
    values are passed per call, so `fn(page)` for a new literal variant of
    a warm chain dispatches the existing executable.

    Dispatch goes through the jit cache's profiled path, so every XLA
    compile a chain triggers is a timed, query-attributed event
    (compile_time_ms). When any entry carries an OperatorStats slot
    (operator-level collection — `tail_slot` is the blocking consumer's
    slot for fused tails), each dispatch is additionally fenced at CHAIN
    granularity and the measured device wall is apportioned across the
    chain's operators by XLA cost analysis: stats collection observes
    the SAME executables the plain query runs — no chain splitting."""
    if not pending and tail_builder is None:
        return None
    key = ("chain",) + chain_keys(pending) + \
        ((tail_key,) if tail_key is not None else ())
    param_groups = chain_params(pending)

    def build():
        fns = [e[1]() for e in pending]
        tail = tail_builder() if tail_builder is not None else None

        def run(page, groups):
            for f, g in zip(fns, groups):
                page = f(page, g)
            if tail is not None:
                page = tail(page)
            return page
        return run
    from trino_tpu.exec.jit_cache import profiled_kernel
    kernel = profiled_kernel(key, build, params=param_groups)

    slots = tuple(e[3] if len(e) > 3 else None for e in pending)
    if all(s is None for s in slots) and tail_slot is None:
        def call(page):
            return kernel(page, param_groups)
        return call
    return _attributed_chain_call(kernel, key, pending, param_groups,
                                  slots, tail_builder, tail_slot)


class DeviceShareSlot:
    """Entry tag that attributes ONLY the device share to a slot — for
    operators whose boundary wrapper already measures inclusive wall and
    counts output rows (masked TopN: its kernel rides the chain, but its
    node's output stream is separately wrapped — full tagging would
    double-count wall and rows on one slot)."""

    def __init__(self, st):
        self.st = st


def _attributed_chain_call(kernel, key, pending, param_groups, slots,
                           tail_builder, tail_slot):
    """The operator-attribution dispatch wrapper: fence once per chain
    dispatch, subtract any compile wall that landed inside the timed
    region (a first-signature dispatch AOT-compiles in place), and split
    the remaining device wall across the tagged operators by the
    profiler's cost weights. Fused chain operators jointly report the
    chain's EXIT rows/pages/bytes (they are one kernel — intermediate
    row counts are not observable without splitting the program, which
    is exactly what this path exists to avoid). Cost weights resolve
    ONCE per stream from the first page (they are ratios of a static
    cost model — per-page re-derivation would just repeat the pytree
    walk the dispatch already paid)."""
    import time as _time

    from trino_tpu.exec import jit_cache
    from trino_tpu.exec.memory import live_page_bytes
    from trino_tpu.obs import profiler

    weights_box: list = []

    def call(page):
        observer = jit_cache.get_observer()
        pre_compile = getattr(observer, "compile_time_s", 0.0)
        t0 = _time.perf_counter()
        out = kernel(page, param_groups)
        jax.block_until_ready(out)
        wall = _time.perf_counter() - t0
        wall = max(wall - (getattr(observer, "compile_time_s", 0.0)
                           - pre_compile), 0.0)
        if observer is not None and hasattr(observer, "add_device_time"):
            observer.add_device_time(wall)
        if not weights_box:
            weights_box.append(profiler.chain_weights(
                key, pending, page, param_groups, tail_builder))
        shares = profiler.apportion(wall, weights_box[0])
        count_exit = isinstance(out, Page)
        n = int(out.num_rows) if count_exit else 0
        nbytes = live_page_bytes(out, n) if count_exit else 0
        for st, share in zip(slots, shares):
            if isinstance(st, DeviceShareSlot):
                st.st.device_s += share     # wall/rows owned by wrapper
            elif st is not None:
                st.wall_s += share
                st.device_s += share
                st.fused = True
                if count_exit:
                    st.output_rows += n
                    st.pages += 1
                    st.output_bytes += nbytes
        if tail_slot is not None and tail_builder is not None:
            tail_slot.device_s += shares[-1]
        return out
    return call


class LocalExecutionPlanner:
    """Single-process executor over one device (LocalQueryRunner's engine)."""

    def __init__(self, metadata: Metadata, session: Session):
        self.metadata = metadata
        self.session = session
        self.page_capacity = int(session.get("page_capacity"))
        # parameterized kernel compilation (expr/hoist.py): on by default;
        # `SET SESSION hoist_literals = false` pins a misbehaving shape
        # back to per-literal compilation for debugging
        self._hoist_on = bool(session.get("hoist_literals"))
        # the query's QueryStatsCollector (obs/stats.py), installed by the
        # owning runner; operator-level instrumentation wraps node
        # boundaries only when collector.operator_level is on (it forces
        # fused chains apart — see obs/stats.py module docstring)
        self.collector = None
        from trino_tpu.exec.memory import QueryMemoryContext
        self.memory = QueryMemoryContext(
            int(session.get("query_max_memory")))
        # which mesh device this executor's reservations live on (None =
        # single-device execution): shard executors set their shard index
        # so the node pool's per-chip gauges attribute HBM to the chip
        # that actually holds it
        self.mem_device: Optional[int] = None
        # fault-tolerance wiring (exec/faults.py + exec/deadline.py),
        # installed by the owning runner; None = no chaos / no limits
        self.faults = None
        self.deadline = None
        # serving-tier scan cache (trino_tpu/serve/caches.ScanCache),
        # installed by the owning runner when scan_cache_enabled: raw
        # staged pages are reusable by ANY query over the same columns
        # (filters/projections chain downstream per query)
        self.scan_cache = None
        # statement parameter values (EXECUTE ... USING), installed by
        # the owning runner: the hoist pass binds BoundParam plan leaves
        # from this tuple, so one cached (value-free) plan re-executes
        # with fresh values through the same warm kernels
        self.exec_params: tuple = ()
        # preemptible sliced execution (exec/sliced/SliceScheduler),
        # installed by the owning runner: leaf page production runs as
        # bounded-work slices with the cooperative boundary (cancel /
        # kill / chaos site `slice`) between them, and scan page
        # capacity is bounded by the slice budget. None = unsliced.
        self.slices = None
        # idempotent-write token (the query id), installed by the owning
        # runner: connector page sinks stage under it and commit on
        # finish, so a retried write attempt can never double-commit
        self.write_token: Optional[str] = None
        # adaptive strategy state (exec/adaptive.AdaptiveQueryState),
        # installed by the owning runner and SHARED across retry
        # attempts: the memory-degrade re-run starts from the modes and
        # heavy keys the failed attempt observed. None = per-execution
        # throwaway state (direct executor use).
        self.adaptive = None
        # device-resident table cache (exec/table_cache.TableCache),
        # installed by the owning runner when table_cache_enabled: hot
        # columns promoted into HBM across queries serve scans with ZERO
        # host->device staging (scan_staging_bytes stays 0 on a hit)
        self.table_cache = None
        # scans promote after this many observed scans of the same
        # (table, columns) working set (session table_cache_min_scans)
        self.table_cache_min_scans = 2
        # per-fragment-attempt memo of resolved table-cache entries
        # (exec/distributed.py shares one dict across a fragment's
        # shard executors, so every shard of one scan sees the SAME
        # hit-or-miss decision); None = resolve per scan (local path)
        self.table_cache_memo: Optional[Dict] = None
        # join dynamic filters routed into connector pruning: scan node
        # id -> TupleDomain registered by the consuming join AFTER its
        # build side collected; the scan's lazy generator intersects it
        # into the split/file/row-group pruning constraint at iteration
        # time (build-before-probe ordering makes that window real)
        self._dyn_domains: Dict[int, object] = {}

    def _checkpoint(self) -> None:
        """Cooperative cancellation/deadline point (page-batch boundary);
        also where a low-memory-killer victim notices its kill mark."""
        if self.deadline is not None:
            self.deadline.check()
        self.memory.poll()

    def _fault_site(self, site: str, detail: str = "") -> None:
        if self.faults is not None:
            self.faults.site(site, detail)

    def _record_spill(self, nbytes: int) -> None:
        """Spill-byte accounting at the host-partition flush sites
        (QueryStats.spilledDataSize analog)."""
        if self.collector is not None:
            self.collector.add_spill(nbytes)

    def _new_spill_store(self, npart: int):
        """A HostPartitionStore charged against the process SpillLedger
        under this query's `spill_max_bytes` budget — spill can no
        longer silently exhaust host RAM (EXCEEDED_SPILL_LIMIT)."""
        from trino_tpu.exec.spill import (SPILL_LEDGER, HostPartitionStore,
                                          resolve_spill_limit)
        return HostPartitionStore(
            npart, ledger=SPILL_LEDGER, query_id=self.memory.query_id,
            limit=resolve_spill_limit(self.session))

    def _adaptive_event(self, name: str, n: int = 1) -> None:
        """Count one adaptive strategy event on the query's collector
        (agg_mode_downgrades / join_recursions / heavy_key_splits /
        spill_fallbacks ... — obs/stats.py)."""
        col = self.collector
        if col is not None:
            setattr(col, name, getattr(col, name) + n)

    def _adaptive_span(self, name: str, **attrs) -> None:
        """Emit an instantaneous strategy-switch trace span: every
        adaptive re-decision is a first-class observable event."""
        from trino_tpu.obs.stats import maybe_span
        with maybe_span(self.collector, name, kind="adaptive", **attrs):
            pass

    def _sliced(self, pages):
        """Wrap a leaf page iterator in the slice loop (exec/sliced/):
        every downstream operator — fused streaming chains and blocking
        collects alike — pulls through the leaf, so a boundary here
        preempts the whole pipeline between device dispatches."""
        if self.slices is None:
            return pages
        return self.slices.run(pages, checkpoint=self._checkpoint,
                               fault_site=self._fault_site)

    # ------------------------------------------------- literal hoisting

    def _hoist(self, expr):
        """Canonicalize one lowered expression: (literal-free tree,
        runtime values tuple). When hoisting is disabled, statement
        parameters still bind — as baked-in Literals (per-value kernel
        keys, the debugging pin's semantics)."""
        if expr is None:
            return expr, ()
        from trino_tpu.expr.hoist import hoist_literals, materialize_bound
        if not self._hoist_on:
            return materialize_bound(expr, self.exec_params), ()
        return hoist_literals(expr, bound=self.exec_params)

    def _hoist_seq(self, exprs):
        """Canonicalize a projection list with one shared values tuple."""
        from trino_tpu.expr.hoist import hoist_literal_seq, \
            materialize_bound
        if not self._hoist_on:
            return tuple(materialize_bound(e, self.exec_params)
                         for e in exprs), ()
        return hoist_literal_seq(exprs, bound=self.exec_params)

    # ------------------------------------------------------------ dispatch

    def execute(self, node: PlanNode) -> PageStream:
        name = type(node).__name__
        method = getattr(self, f"_exec_{name}", None)
        if method is None:
            raise ExecutionError(f"no executor for {name}")
        stream = method(node)
        if self.collector is None or not self.collector.operator_level:
            return stream
        return self._instrument(node, stream)

    def _slot(self, node: PlanNode):
        """The node's OperatorStats slot under operator-level collection
        (blocking nodes hand it to compose_chain as tail_slot so a fused
        tail's device share attributes to them), else None."""
        if self.collector is None or not self.collector.operator_level:
            return None
        return self.collector.register(node)

    def _instrument(self, node: PlanNode, stream: PageStream) -> PageStream:
        """Operator-level stats (EXPLAIN ANALYZE / collect_operator_stats)
        WITHOUT chain splitting (round 13). A streaming node's stream
        still carries its pending fused ops: tag the entries this node
        contributed (the ones its children haven't tagged) with the
        node's stats slot and hand the stream on UNCHANGED — the fused
        chain composes exactly as it would uninstrumented, and
        compose_chain apportions each dispatch's measured device wall
        across the tagged operators by XLA cost analysis. Only
        already-materialized boundaries (leaf scans, blocking operators)
        get the classic counting wrapper: there is no fused chain to
        split there, so per-page row/byte counts and inclusive wall are
        free of observer effects; under EXPLAIN ANALYZE `fence`
        additionally pins their asynchronously dispatched device work."""
        import time as _time

        from trino_tpu.exec.memory import live_page_bytes
        st = self.collector.register(node)
        if stream.pending:
            pending = tuple(
                e if len(e) > 3 and e[3] is not None
                else (e[0], e[1], e[2], st)
                for e in stream.pending)
            return PageStream(stream.pages, stream.symbols, pending)
        fence = self.collector.fence

        def gen():
            it = stream.iter_pages()
            while True:
                t0 = _time.perf_counter()
                try:
                    page = next(it)
                except StopIteration:
                    st.wall_s += _time.perf_counter() - t0
                    return
                if fence:
                    jax.block_until_ready(page)
                n = int(page.num_rows)
                st.output_rows += n
                st.wall_s += _time.perf_counter() - t0
                st.pages += 1
                st.output_bytes += live_page_bytes(page, n)
                yield page
        return PageStream(gen(), stream.symbols)

    # ---------------------------------------------------------------- leaf

    def _exec_TableScanNode(self, node: TableScanNode) -> PageStream:
        conn = self.metadata.connector(node.catalog)
        columns = [c for _, c in node.assignments]
        cap = self._scan_capacity(conn, node)
        symbols = tuple(s for s, _ in node.assignments)
        system = node.catalog == "system"
        st = node.table.name
        tkey = (node.catalog, st.schema, st.table)
        col = self.collector
        # device-resident table cache FIRST: full columns already in HBM
        # serve any column subset at any capacity with zero host->device
        # staging (scan_staging_bytes stays 0 — the counter proof)
        tcache = None if system else self.table_cache
        col_names = [c.name for c in columns]
        # generation snapshot BEFORE any scanning: a promotion built
        # from pre-INSERT pages must not land after the invalidation
        tgen = None if tcache is None else tcache.generation()
        if tcache is not None:
            entry = tcache.lookup(tkey, col_names)
            if entry is not None:
                if col is not None:
                    col.table_cache_hit()
                from trino_tpu.exec.table_cache import build_pages
                resident = build_pages(entry, col_names, cap)

                def gen_resident(pages=resident):
                    for page in pages:
                        self._checkpoint()
                        yield page
                return PageStream(self._sliced(gen_resident()), symbols)
            if col is not None:
                col.table_cache_miss()
        cache = self.scan_cache
        key = None
        if cache is not None and not system:
            # system.runtime tables materialize live engine state at
            # scan time — caching them would freeze it. The key carries
            # the handle's pushed-down constraint and limit: a pruning
            # connector's page set is a function of both, so a LIMIT- or
            # domain-truncated scan must never serve a full one.
            key = (tkey, tuple((c.name, c.ordinal) for c in columns),
                   cap, node.table.constraint.freeze(), node.table.limit)
            staged = cache.get(key)
            if staged is not None:
                if col is not None:
                    col.scan_cache_hit()
                # staged pages are already on device: a hot working set
                # promotes into the table cache from HERE (device
                # concats, no host re-read)
                self._maybe_promote(tcache, tkey, node, staged, tgen)

                def gen_hit(pages=staged):
                    for page in pages:
                        self._checkpoint()
                        yield page
                return PageStream(self._sliced(gen_hit()), symbols)
            if col is not None:
                col.scan_cache_miss()
        gen_seen = None if key is None else cache.generation()

        def gen():
            from trino_tpu.exec.memory import page_bytes
            # dynamic filters (registered by a consuming join after its
            # build collected — strictly before this generator is
            # pulled) intersect into the pruning constraint so the
            # connector can skip whole files/row groups, not just rows
            handle, dyn_applied = self._effective_handle(conn, node)
            splits = conn.split_manager.get_splits(handle, target_splits=1)
            # promotion decision up front: a FULL page set (no limit, no
            # effective pruning) of a hot-enough working set stages for
            # the device table cache even when the scan cache is off
            promote = False
            if tcache is not None and not dyn_applied \
                    and node.table.limit is None \
                    and (not getattr(conn.metadata, "supports_zone_maps",
                                     False)
                         or handle.constraint.is_all()):
                count = tcache.note_scan(tkey, col_names)
                promote = count >= max(
                    int(self.table_cache_min_scans), 1) \
                    and tcache.should_promote(tkey, col_names)
            staging = [] if (key is not None and not dyn_applied) \
                or promote else None
            # session verify level + the query's fault injector ride a
            # connector thread-local down to the read path (the SPI scan
            # signature carries no session); reset in the finally so a
            # later bare read on this thread falls back to the default
            setopt = getattr(conn, "set_scan_options", None)
            if setopt is not None:
                setopt(verify=str(self.session.get(
                           "lake_verify_checksums")),
                       faults=self.faults)
            try:
                for split in splits:
                    self._fault_site("scan", str(node.table))
                    for page in conn.page_source.pages(split, columns,
                                                       cap):
                        self._checkpoint()
                        if col is not None:
                            col.add_scan_staging(page_bytes(page))
                        if staging is not None:
                            staging.append(page)
                        yield page
            finally:
                self._drain_scan_stats(conn)
                if setopt is not None:
                    setopt()
            if staging is not None and key is not None and not dyn_applied:
                # gen_seen guards the race with a concurrent INSERT: a
                # scan that started pre-change must not publish post-
                # invalidation (same discipline as PlanCache.put). A
                # dynamically-pruned page set is keyed on the STATIC
                # constraint, so it must not publish at all.
                cache.put(key, staging, gen=gen_seen)
            if promote and staging:
                counts = [int(c) for c in jax.device_get(
                    [p.num_rows for p in staging])]
                tcache.promote_from_pages(
                    tkey, [(c.name, c) for _, c in node.assignments],
                    staging, counts, device=self.mem_device,
                    collector=col, gen=tgen)
        return PageStream(self._sliced(gen()), symbols)

    def _effective_handle(self, conn, node: TableScanNode):
        """(handle for split pruning, dynamic-filter-applied flag): the
        static pushed-down constraint, intersected with any registered
        join dynamic filter, or cleared entirely when the session pins
        zone-map pruning off (lake_zone_maps_enabled = false)."""
        import dataclasses as _dc

        from trino_tpu.predicate import TupleDomain
        handle = node.table
        prunes = getattr(conn.metadata, "supports_zone_maps", False)
        if prunes and not bool(
                self.session.get("lake_zone_maps_enabled")):
            return (_dc.replace(handle, constraint=TupleDomain.all()),
                    False)
        dyn = self._dyn_domains.get(id(node))
        if dyn is None or not prunes:
            return handle, False
        return (_dc.replace(handle,
                            constraint=handle.constraint.intersect(dyn)),
                True)

    def _drain_scan_stats(self, conn) -> None:
        """Fold the connector's per-scan prune counters (thread-local —
        the scan ran on this thread) into the query stats."""
        take = getattr(conn, "take_scan_stats", None)
        if take is None:
            return
        d = take() or {}
        if self.collector is not None and d:
            self.collector.add_pruned(d.get("files_pruned", 0),
                                      d.get("row_groups_pruned", 0))

    def _maybe_promote(self, tcache, tkey, node: TableScanNode,
                       pages, gen=None) -> None:
        """Promote a hot (table, columns) working set into the device
        table cache from its already-staged pages. Only FULL page sets
        are admissible: a handle with a pushed-down constraint or limit
        on a pruning connector may cover a subset of the table."""
        if tcache is None or not pages:
            return
        if node.table.limit is not None:
            return
        if getattr(self.metadata.connector(node.catalog).metadata,
                   "supports_zone_maps", False) \
                and not node.table.constraint.is_all():
            return
        names = [c.name for _, c in node.assignments]
        if tcache.note_scan(tkey, names) < max(
                int(self.table_cache_min_scans), 1):
            return
        if not tcache.should_promote(tkey, names):
            return
        counts = [int(c) for c in jax.device_get(
            [p.num_rows for p in pages])]
        tcache.promote_from_pages(
            tkey, [(c.name, c) for _, c in node.assignments], pages,
            counts, device=self.mem_device, collector=self.collector,
            gen=gen)

    def register_dynamic_domain(self, scan_node, column: str, typ,
                                lo, hi) -> None:
        """A consuming join publishes its collected build-side key range
        as a TupleDomain for `scan_node` — the scan's generator (not yet
        pulled: build-before-probe) folds it into file/row-group
        pruning. Values are raw internal representation, matching the
        zone maps."""
        from trino_tpu.predicate import Domain, Range, TupleDomain
        dom = TupleDomain.with_column_domains(
            {column: Domain.from_range(typ, Range.between(lo, hi))})
        prev = self._dyn_domains.get(id(scan_node))
        self._dyn_domains[id(scan_node)] = \
            dom if prev is None else prev.intersect(dom)
        from trino_tpu.obs.stats import maybe_span
        with maybe_span(self.collector, "dynamic-filter-pushdown",
                        kind="scan", column=column, low=str(lo),
                        high=str(hi)):
            pass

    def _dyn_scan_target(self, subtree, symbol_name: str):
        """The TableScanNode under `subtree` whose output directly
        carries `symbol_name`, reached only through row-restricting
        nodes (filter/project/join/semijoin — pruning its rows by a key
        bound the join will enforce anyway cannot change results; a
        window/limit/topn in between could, so the walk stops there),
        on a connector that prunes by zone maps. None when absent."""
        from trino_tpu.planner.nodes import (FilterNode, JoinNode,
                                             ProjectNode, SemiJoinNode)
        stack = [subtree]
        while stack:
            n = stack.pop()
            if isinstance(n, TableScanNode):
                for s, ch in n.assignments:
                    if s.name == symbol_name:
                        conn = self.metadata.connector(n.catalog)
                        if getattr(conn.metadata, "supports_zone_maps",
                                   False):
                            return n, ch.name, ch.type
                continue
            if isinstance(n, (FilterNode, ProjectNode, JoinNode,
                              SemiJoinNode)):
                stack.extend(n.sources)
        return None

    def _scan_capacity(self, conn, node: TableScanNode) -> int:
        """Size scan pages to the table: one big page per split keeps the
        steady state at a handful of device calls instead of a Python loop
        over thousands of 64Ki pages (ScanFilterAndProjectOperator's whole
        point is amortizing per-page overhead; on TPU the analog is fewer,
        larger fused kernel launches)."""
        cap = self.page_capacity
        try:
            stats = conn.metadata.get_table_statistics(node.table)
            rows = int(stats.row_count) if stats and stats.row_count else 0
        except Exception:
            rows = 0
        if rows > cap:
            max_cap = int(self.session.get("scan_page_capacity"))
            cap = min(_next_pow2(rows), max_cap)
        if self.slices is not None:
            # one scan page must never exceed a slice: a bigger page is
            # a single un-preemptible kernel launch, exactly what the
            # sliced executor exists to bound
            cap = min(cap, self.slices.capacity_cap(self.page_capacity))
        return cap

    def _exec_ValuesNode(self, node: ValuesNode) -> PageStream:
        cols = []
        n = len(node.rows)
        cap = max(_next_pow2(n), 8)
        for i, sym in enumerate(node.symbols):
            typ = sym.type
            vals = []
            valid = []
            for row in node.rows:
                lit = row[i]
                if not isinstance(lit, Literal):
                    raise ExecutionError("VALUES row is not literal")
                vals.append(0 if lit.value is None else lit.value)
                valid.append(lit.value is not None)
            if T.is_string(typ):
                from trino_tpu.page import Dictionary
                d, codes = Dictionary.build(np.asarray(
                    [v if isinstance(v, str) else "" for v in vals],
                    dtype=object))
                arr = np.zeros(cap, dtype=np.int32)
                arr[:n] = codes
                col = Column(jnp.asarray(arr), _valid_arr(valid, cap), typ, d)
            else:
                arr = np.zeros(cap, dtype=T.to_numpy_dtype(typ))
                arr[:n] = vals
                col = Column(jnp.asarray(arr), _valid_arr(valid, cap), typ,
                             None)
            cols.append(col)
        page = Page(tuple(cols), n)
        return PageStream(iter([page]), node.symbols)

    # ----------------------------------------------------------- streaming

    def _exec_FilterNode(self, node: FilterNode) -> PageStream:
        # Filter(SemiJoin) fuses into semi/anti probe (LocalExecutionPlanner
        # visitFilter's special-cased semi-join consumption); complex match
        # usage (the flag inside OR/CASE — q10/q35-style stacked EXISTS)
        # falls back to the generic mark-column path
        if isinstance(node.source, SemiJoinNode) and \
                self._semijoin_filter_mode(node) is not None:
            return self._exec_semijoin_filter(node)
        src = self.execute(node.source)
        lay, typ = _layout(src.symbols)
        pred, prm = self._hoist(lower_expr(node.predicate, lay, typ))
        return PageStream(
            src.pages, src.symbols,
            src.pending + ((("filter", pred),
                            lambda: lambda p, g, f=compile_filter(pred):
                            p.filter(f(p, g)), prm),))

    def _exec_ProjectNode(self, node: ProjectNode) -> PageStream:
        src = self.execute(node.source)
        lay, typ = _layout(src.symbols)
        exprs, prm = self._hoist_seq(
            tuple(lower_expr(e, lay, typ) for _, e in node.assignments))

        def builder():
            fns = [compile_expression(e) for e in exprs]
            return lambda page, g: Page(tuple(fn(page, g) for fn in fns),
                                        page.num_rows)
        return PageStream(src.pages, tuple(s for s, _ in node.assignments),
                          src.pending + ((("project", exprs), builder,
                                          prm),))

    def _exec_LimitNode(self, node: LimitNode) -> PageStream:
        src = self.execute(node.source)

        def gen():
            remaining = node.count
            for page in src.iter_pages():
                n = int(page.num_rows)
                if n >= remaining:
                    yield Page(page.columns, remaining)
                    return
                remaining -= n
                yield page
        return PageStream(gen(), src.symbols)

    def _exec_OffsetNode(self, node: OffsetNode) -> PageStream:
        src = self.execute(node.source)

        def gen():
            to_skip = node.count
            for page in src.iter_pages():
                n = int(page.num_rows)
                if to_skip >= n:
                    to_skip -= n
                    continue
                if to_skip > 0:
                    idx = jnp.arange(page.capacity, dtype=jnp.int32) + to_skip
                    gathered = tuple(c.gather(idx) for c in page.columns)
                    page = Page(gathered, n - to_skip)
                    to_skip = 0
                yield page
        return PageStream(gen(), src.symbols)

    # ------------------------------------------------------------ blocking

    def _collect(self, stream: PageStream) -> Optional[Page]:
        """Materialize a stream (blocking-operator input). The result is
        reserved against query_max_memory: blocking materializations are
        what consumes HBM (streamed pages flow through one fused kernel).
        Freed at operator scope via _free_collected."""
        from trino_tpu.exec.memory import page_bytes
        page = self.merge_counted(list(stream.iter_pages()))
        if page is None:
            return None
        # chaos site `memory`: injected node-pool pressure at the point a
        # real reservation would hit the killer
        self._fault_site("memory", "collect")
        self.memory.reserve(page_bytes(page), "collect",
                            device=self.mem_device)
        return page

    def merge_counted(self, pages: List[Page]) -> Optional[Page]:
        """Concatenate pages ON DEVICE (dynamic_update_slice cascade) with
        ONE batched count fetch — the host bounce (concat_pages) moved
        every live row through the tunnel, and a per-page num_rows check
        costs a ~95ms round trip each. Pages shrink to their live pow2
        first so the concat transient is O(live rows), not O(sum of scan
        capacities). Shared by blocking collects and the distributed
        runner's per-shard fragment outputs."""
        page, _ = self.merge_counted_rows(pages)
        return page

    def merge_counted_rows(self, pages: List[Page]
                           ) -> Tuple[Optional[Page], int]:
        """merge_counted plus the live total it already fetched — the
        adaptive aggregation's reduction-ratio denominator, free at
        every compaction boundary."""
        if not pages:
            return None, 0
        counts = [int(c) for c in jax.device_get(
            [p.num_rows for p in pages])]
        total = sum(counts)
        if total == 0:
            return None, 0
        live = [self._tight(p, c) for p, c in zip(pages, counts) if c > 0]
        return self._merge_buf(live, total), total

    @staticmethod
    def _tight(page: Page, n: int) -> Page:
        """Shrink a page to the pow2 envelope of its live count (free
        device slice; downstream sorts/builds then run at live size)."""
        tight = _next_pow2(max(n, 1))
        if page.capacity > 2 * tight:
            return page.shrink_to(tight)
        return page

    def _device_concat(self, pages: List[Page]) -> Page:
        """Jitted device-side page concatenation (page.device_concat) —
        one compiled program per (capacities, ncols) combination."""
        from trino_tpu.page import device_concat
        key = ("dconcat", tuple(p.capacity for p in pages),
               pages[0].num_columns)
        op = cached_kernel(key, lambda: lambda *ps: device_concat(ps))
        return op(*pages)

    def _coalesce_stream(self, stream: PageStream,
                         target_rows: Optional[int] = None,
                         prefilter=None) -> PageStream:
        """Batch filtered pages into few large buffers before a probe.

        A probe kernel launch has a large fixed cost (sort-engine passes at
        static capacity, regardless of live rows): round-4 profiling showed
        q3 SF10 paying ~23s across 19 per-page probe calls on ~2M-live
        pages. Lookahead windows keep the transfer discipline (one batched
        count fetch per window, JAX dispatch stays async).

        `prefilter` is an optional (op, args) dynamic filter (build-side
        key range) applied per page BEFORE buffering; it is adaptive — if
        the first window prunes less than 25% of rows, the filter is
        dropped for the rest of the stream (its compaction sort would only
        add cost on uniformly-spread keys)."""
        if target_rows is None:
            target_rows = int(self.session.get("probe_coalesce_rows"))
        row_bytes = 8 * max(len(stream.symbols), 1)
        # cap a buffer at ~512MB regardless of width: the probe's stable
        # sort carries every column as payload, and a wider buffer's sort
        # scratch is what exhausted the device at SF100 (measured: 21M-row
        # x 11-operand sorts fail, 4M-row buffers stream 600M rows fine)
        target_rows = max(1 << 16, min(target_rows, (1 << 29) // row_bytes))

        def gen():
            import itertools
            it = stream.iter_pages()
            buf: List[Page] = []
            buf_rows = 0
            use_df = prefilter is not None
            df_measured = False
            while True:
                window = list(itertools.islice(it, 8))
                if not window:
                    break
                if use_df:
                    pf_op, pf_args = prefilter
                    filtered = [pf_op(p, *pf_args) for p in window]
                    if not df_measured:
                        pre = jax.device_get(
                            [p.num_rows for p in window])
                        post = jax.device_get(
                            [p.num_rows for p in filtered])
                        df_measured = True
                        if sum(int(c) for c in post) > 0.75 * max(
                                sum(int(c) for c in pre), 1):
                            use_df = False   # not selective enough
                        window = filtered
                        counts = post
                    else:
                        window = filtered
                        counts = jax.device_get(
                            [p.num_rows for p in window])
                else:
                    counts = jax.device_get([p.num_rows for p in window])
                for p, c in zip(window, counts):
                    n = int(c)
                    if n == 0:
                        continue
                    if n >= target_rows:
                        yield self._merge_buf([p], n)
                        continue
                    buf.append(self._tight(p, n))
                    buf_rows += n
                    if buf_rows >= target_rows:
                        yield self._merge_buf(buf, buf_rows)
                        buf, buf_rows = [], 0
            if buf:
                yield self._merge_buf(buf, buf_rows)
        return PageStream(gen(), stream.symbols)

    def _merge_buf(self, buf: List[Page], rows: int) -> Page:
        page = buf[0] if len(buf) == 1 else self._device_concat(buf)
        return self._tight(page, rows)

    def _free_collected(self, page: Optional[Page]) -> None:
        """Release a _collect reservation at operator scope (the reference
        frees per-operator memory contexts on finish — without this a
        query's sequential peak would be accounted as the SUM of every
        build side / sort input ever held)."""
        if page is not None:
            from trino_tpu.exec.memory import page_bytes
            self.memory.free(page_bytes(page), "collect",
                             device=self.mem_device)

    def _exec_AggregationNode(self, node: AggregationNode) -> PageStream:
        fused = self._mxu_agg_join(node)
        if fused is not None:
            return fused
        src = self.execute(node.source)
        return self._agg_over_stream(node, src)

    def _agg_over_stream(self, node: AggregationNode,
                         src: PageStream) -> PageStream:
        lay, typ = _layout(src.symbols)
        key_channels = [lay[s.name] for s in node.group_by]
        specs = []
        for out_sym, call in node.aggregations:
            if call.args:
                arg = call.args[0]
                assert isinstance(arg, SymbolRef)
                input_ch: Optional[int] = lay[arg.name]
                in_type: Optional[T.Type] = typ[arg.name]
            else:
                input_ch, in_type = None, None
            in2_ch = in2_type = None
            if len(call.args) > 1:
                arg2 = call.args[1]
                assert isinstance(arg2, SymbolRef)
                in2_ch, in2_type = lay[arg2.name], typ[arg2.name]
            mask_ch = None
            if call.filter is not None:
                assert isinstance(call.filter, SymbolRef)
                mask_ch = lay[call.filter.name]
            specs.append(AggSpec(call.name, input_ch, in_type, mask_ch,
                                 call.distinct, in2_ch, in2_type))

        key_channels_t = tuple(key_channels)
        specs_t = tuple(specs)
        from trino_tpu.ops.aggregate import (COLLECT_AGGREGATES,
                                             SINGLE_STEP_AGGREGATES,
                                             group_max_size)
        if any(s.distinct or s.name in SINGLE_STEP_AGGREGATES
               for s in specs):
            # DISTINCT needs every row of a group in one kernel call
            # (distinctness is a property of the whole group, not a page),
            # so collect and run one SINGLE-step aggregation — the
            # MarkDistinct + filtered-agg plan collapsed into the sort-based
            # kernel (ops/aggregate.py:_distinct_first_mask). Collect
            # aggregates (array_agg/histogram/map_agg) additionally size
            # their list layout with a max-group-size pre-pass.
            needs_len = any(s.name in COLLECT_AGGREGATES for s in specs)

            def gen_distinct():
                page = self._collect(src)
                if page is None:
                    if not key_channels:
                        yield self._empty_global_agg(node, specs)
                    return
                L = None
                if needs_len:
                    szop = cached_kernel(
                        ("agg-groupmax", key_channels_t),
                        lambda: group_max_size(key_channels))
                    got = max(int(jax.device_get(szop(page))), 1)
                    # small pow2 (not the 1024-floor page helper): the
                    # element plane is [capacity, L]
                    L = 1 << (got - 1).bit_length() if got > 1 else 1
                single_op = cached_kernel(
                    ("agg-single", key_channels_t, specs_t, L),
                    lambda: hash_aggregate(key_channels, specs,
                                           Step.SINGLE, list_len=L))
                try:
                    yield single_op(page)
                finally:
                    self._free_collected(page)
            return PageStream(gen_distinct(), node.outputs)
        # fuse the upstream filter/project chain into the partial-agg kernel:
        # scan -> filter -> project -> partial agg is ONE device program per
        # page (ScanFilterAndProjectOperator + partial-agg fusion)
        partial_op = compose_chain(
            src.pending, ("agg-partial", key_channels_t, specs_t),
            lambda: hash_aggregate(key_channels, specs, Step.PARTIAL),
            tail_slot=self._slot(node))
        # the adaptive bypass kernel: same fused chain, but the tail maps
        # each row to a PARTIAL-layout state row with NO sort (O(n) — the
        # "Partial Partial Aggregates" bypass for effectively-high NDV);
        # layout-identical to partial_op's output so both mix in one buffer
        from trino_tpu.ops.aggregate import passthrough_partial
        bypass_op = compose_chain(
            src.pending, ("agg-bypass", key_channels_t, specs_t),
            lambda: passthrough_partial(key_channels, specs),
            tail_slot=self._slot(node))

        # FINAL consumes the partial layout: keys first, then each agg's
        # state columns in sequence
        from trino_tpu.ops.aggregate import get_aggregate
        nkeys = len(key_channels)
        state_channels = []
        ch = nkeys
        for spec in specs:
            fn = get_aggregate(spec.name, spec.input_type)
            k = len(fn.state(spec.input_type))
            state_channels.append(list(range(ch, ch + k)))
            ch += k
        final_keys = list(range(nkeys))
        final_op = cached_kernel(
            ("agg-final", nkeys, specs_t),
            lambda: hash_aggregate(final_keys, specs, Step.FINAL,
                                   state_channels))

        intermediate_op = cached_kernel(
            ("agg-intermediate", nkeys, specs_t),
            lambda: hash_aggregate(final_keys, specs, Step.INTERMEDIATE,
                                   state_channels))

        def gen():
            # no per-page num_rows sync: empty pages produce neutral partial
            # states that merge correctly (the sync was a tunnel round-trip
            # per page on remote TPU). Over-budget partial buffers compact
            # via Step.INTERMEDIATE; if groups aren't collapsing (q18-class
            # high-cardinality GROUP BY) the compacted states spill to host
            # hash partitions and finalize one bounded partition at a time
            # (SpillableHashAggregationBuilder.java:47 re-thought — see
            # exec/spill.py). ADAPTIVE: an AggModeController watches the
            # observed reduction ratio at every compaction boundary and
            # walks full -> shrunken -> bypass (exec/adaptive.py) when NDV
            # turns out effectively high, re-upgrading when it recovers;
            # decisions happen only between device dispatches, so the
            # sliced executor's cooperative boundary stays responsive.
            from trino_tpu.exec.adaptive import (AdaptiveQueryState,
                                                 AggMode)
            from trino_tpu.exec.memory import page_bytes
            from trino_tpu.exec.spill import partition_by_hash
            threshold = int(self.session.get("agg_spill_threshold_bytes"))
            npart = int(self.session.get("spill_partition_count"))
            spillable = bool(self.session.get("spill_enabled")) \
                and bool(key_channels)
            ctl = None
            # adaptive modes only when spill can absorb them: without a
            # flush boundary there is no observation to correct a wrong
            # CBO estimate, and shrunken/bypass states would accumulate
            # O(rows) with nothing bounding them
            if bool(self.session.get("adaptive_partial_agg")) \
                    and spillable:
                state = self.adaptive if self.adaptive is not None \
                    else AdaptiveQueryState()
                # STRUCTURAL key (group-by + aggregate output symbol
                # names), not node id: a degrade re-run that re-plans
                # past a missed plan cache must still find the failed
                # attempt's controller; the output symbols disambiguate
                # two operators grouping by the same keys
                ctl = state.agg_controller(
                    ("agg", tuple(s.name for s in node.group_by),
                     tuple(s.name for s, _ in node.aggregations)),
                    ndv=getattr(node, "ndv_estimate", None),
                    rows=getattr(node, "rows_estimate", None),
                    allow_bypass=spillable)
            store = None
            part_ops: Dict[int, object] = {}
            buf: List[Page] = []
            buf_bytes = 0
            any_pages = False
            # the ratio denominator is RAW input rows in EVERY mode
            # (full's per-page partial must not shrink it, or
            # key-clustered input oscillates between metrics): raw page
            # counts batch-fetch at the compaction boundary, and a
            # re-buffered compacted page carries its history forward
            raw_counts: List[object] = []
            raw_carry = 0

            def part_op_for(salt: int):
                op = part_ops.get(salt)
                if op is None:
                    op = part_ops[salt] = cached_kernel(
                        ("agg-spill-part", nkeys, npart, salt),
                        lambda: partition_by_hash(final_keys, npart,
                                                  salt=salt))
                return op

            def compact_buffer():
                nonlocal buf, buf_bytes
                merged, rows_in = self.merge_counted_rows(buf)
                buf, buf_bytes = [], 0
                if merged is None:
                    return None, rows_in, 0
                out = intermediate_op(merged)
                n = int(jax.device_get(out.num_rows))
                if n == 0:
                    return None, rows_in, 0
                return self._tight(out, n), rows_in, n

            def raw_rows_in():
                nonlocal raw_counts, raw_carry
                total = raw_carry + sum(
                    int(c) for c in jax.device_get(raw_counts)) \
                    if raw_counts else raw_carry
                raw_counts = []
                return total

            def observe(rows_in, groups_out):
                if ctl is None or rows_in <= 0:
                    return
                transition = ctl.observe(rows_in, groups_out)
                if transition is not None:
                    self._adaptive_event(
                        "agg_mode_downgrades" if transition == "downgrade"
                        else "agg_mode_upgrades")
                    self._adaptive_span(
                        "agg-mode-switch", transition=transition,
                        mode=ctl.mode,
                        ratio=round(ctl.last_ratio or 0.0, 4))

            def spill(combined):
                nonlocal store
                self._fault_site("spill", "agg")
                self._record_spill(page_bytes(combined))
                if store is None:
                    store = self._new_spill_store(npart)
                sorted_pg, counts = part_op_for(0)(combined)
                store.spill_partitioned(sorted_pg,
                                        jax.device_get(counts))

            try:
                for page in src.pages:
                    self._checkpoint()
                    any_pages = True
                    mode = ctl.mode if ctl is not None else AggMode.FULL
                    pp = partial_op(page) if mode == AggMode.FULL \
                        else bypass_op(page)
                    buf.append(pp)
                    raw_counts.append(page.num_rows)
                    buf_bytes += page_bytes(pp)
                    if not (spillable and buf_bytes >= threshold):
                        continue
                    if mode == AggMode.BYPASS:
                        probe = ctl.should_probe()
                        ctl.note_flush()
                        if not probe:
                            # full bypass: raw per-row states straight to
                            # host partitions — zero reduction work (the
                            # per-partition finalize groups ONCE)
                            merged, _ = self.merge_counted_rows(buf)
                            buf, buf_bytes = [], 0
                            raw_counts, raw_carry = [], 0
                            if merged is not None:
                                spill(merged)
                            continue
                    elif ctl is not None:
                        ctl.note_flush()
                    rows_raw = raw_rows_in()
                    combined, _rows_states, groups_out = compact_buffer()
                    observe(rows_raw, groups_out)
                    if combined is None:
                        raw_carry = 0
                        continue
                    cb = page_bytes(combined)
                    if cb >= threshold // 2:
                        spill(combined)        # groups aren't collapsing
                        raw_carry = 0
                    else:
                        buf, buf_bytes = [combined], cb
                        raw_carry = rows_raw   # history rides along

                if store is None:
                    if not any_pages:
                        if not key_channels:
                            yield self._empty_global_agg(node, specs)
                        return
                    merged, _ = self.merge_counted_rows(buf)
                    if merged is None:
                        # every input page was empty (grouped agg -> no
                        # output; global agg partials always carry one
                        # state row, so a None merge implies zero rows)
                        if not key_channels:
                            yield self._empty_global_agg(node, specs)
                        return
                    yield final_op(merged)
                    return
                rows_raw = raw_rows_in()
                combined, _rows_states, groups_out = compact_buffer()
                observe(rows_raw, groups_out)
                if combined is not None:
                    spill(combined)
                yield from self._finalize_agg_spill(
                    store, 0, final_op, intermediate_op, part_op_for,
                    final_keys, threshold)
            finally:
                if store is not None:
                    store.close()
        return PageStream(gen(), node.outputs)

    def _finalize_agg_spill(self, store, depth: int, final_op,
                            intermediate_op, part_op_for, key_idxs,
                            threshold: int) -> Iterator[Page]:
        """Finalize spilled hash partitions with the robust dynamic
        hybrid discipline: a partition within budget restages and
        finalizes in one kernel; one still over budget first splits out
        heavy-hitter keys (re-hashing can NEVER separate one key's rows
        — they fold chunk-wise instead, INTERMEDIATE collapses a heavy
        key to ONE state row per chunk), then recursively repartitions
        with a fresh hash salt up to `spill_max_recursion`, and at max
        depth falls back to the bounded chunked fold — graceful
        degradation instead of an over-budget restage OOM."""
        from trino_tpu.exec.memory import page_bytes
        from trino_tpu.exec.spill import (detect_partition_heavy_keys,
                                          partition_key_hashes,
                                          split_partition)
        threshold = self._spill_budget(threshold)
        max_rec = int(self.session.get("spill_max_recursion"))
        heavy_limit = int(self.session.get("spill_heavy_key_limit"))
        npart = store.npart

        def stage_final(p: int, nrows: int) -> Iterator[Page]:
            pg = store.restage(p, _next_pow2(max(nrows, 1)))
            store.drop(p)
            held = page_bytes(pg)
            self.memory.reserve(held, "agg-restage",
                                device=self.mem_device)
            try:
                yield final_op(pg)
            finally:
                self.memory.free(held, "agg-restage",
                                 device=self.mem_device)

        for p in range(npart):
            self._checkpoint()
            nrows = store.partition_rows(p)
            if nrows == 0:
                continue
            if store.partition_bytes(p) <= max(threshold, 1):
                yield from stage_final(p, nrows)
                continue
            chunk_rows = store.chunk_rows_for(p, threshold)
            if heavy_limit > 0 and depth < max_rec and npart > 1:
                hashes = partition_key_hashes(store, p, key_idxs)
                heavy = detect_partition_heavy_keys(
                    store, p, key_idxs, heavy_limit,
                    max(2, nrows // (2 * max(npart, 2))),
                    piece_hashes=hashes)
                if len(heavy):
                    self._fault_site("spill", "agg-heavy")
                    self._adaptive_event("heavy_key_splits")
                    self._adaptive_span("agg-heavy-split", depth=depth,
                                        keys=int(len(heavy)))
                    sub = split_partition(store, p, key_idxs, heavy,
                                          piece_hashes=hashes)
                    try:
                        yield from self._agg_chunk_fold(
                            sub, 0, final_op, intermediate_op,
                            chunk_rows)
                    finally:
                        sub.close()
                    nrows = store.partition_rows(p)
                    if nrows == 0:
                        continue
                    if store.partition_bytes(p) <= max(threshold, 1):
                        yield from stage_final(p, nrows)
                        continue
            if depth >= max_rec or npart <= 1:
                # bounded-depth guarantee: an irreducible partition
                # folds in bounded chunks instead of recursing forever
                # (npart <= 1: re-hashing cannot redistribute at all)
                self._fault_site("spill", "agg-fallback")
                self._adaptive_event("spill_fallbacks")
                self._adaptive_span("agg-spill-fallback", depth=depth)
                yield from self._agg_chunk_fold(
                    store, p, final_op, intermediate_op, chunk_rows)
                continue
            # recursive repartition under a fresh hash salt: the same
            # keys REDISTRIBUTE across the child partitions
            self._fault_site("spill", "agg-recurse")
            self._adaptive_event("agg_recursions")
            self._adaptive_span("agg-spill-recurse", depth=depth + 1)
            child = self._new_spill_store(npart)
            try:
                op = part_op_for(depth + 1)
                # drain: each transferred piece releases before the
                # child charges the next — no transient double-hold of
                # the partition against the spill budget
                for chunk in store.drain_partition_chunks(p, chunk_rows):
                    self._checkpoint()
                    sorted_pg, counts = op(chunk)
                    child.spill_partitioned(sorted_pg,
                                            jax.device_get(counts))
                store.drop(p)
                yield from self._finalize_agg_spill(
                    child, depth + 1, final_op, intermediate_op,
                    part_op_for, key_idxs, threshold)
            finally:
                child.close()

    def _agg_chunk_fold(self, store, p: int, final_op, intermediate_op,
                        chunk_rows: int) -> Iterator[Page]:
        """Bounded chunked merge of one partition: restage <= chunk_rows
        at a time, INTERMEDIATE-fold into the carried state, finalize
        once — the device transient is one chunk plus the state (which
        is the partition's true group count, the output-size floor no
        strategy can beat). The heavy-key path and the max-recursion
        fallback both bottom out here."""
        state = None
        for chunk in store.drain_partition_chunks(p, chunk_rows):
            self._checkpoint()
            merged = chunk if state is None \
                else self._device_concat([state, chunk])
            out = intermediate_op(merged)
            n = int(jax.device_get(out.num_rows))
            state = self._tight(out, n) if n else None
        store.drop(p)
        if state is not None:
            yield final_op(state)

    # aggregate functions the matmul path can factor through per-key
    # build vectors (arXiv 2206.04995's M = A·Bᵀ multiplicities)
    _MXU_FUSABLE_AGGS = ("count", "sum")

    def _mxu_agg_join(self, node: AggregationNode
                      ) -> Optional[PageStream]:
        """The many-to-many AGGREGATING join on the matrix unit (the
        TPC-DS q64/q72 shapes — ops/join_mxu.py): when a SINGLE
        aggregation consumes an INNER single-clause equi-join directly
        (optionally through a pure column-select projection), every
        group key is probe-side, and every aggregate is a factorable
        COUNT/SUM over one side's column, the join's match
        multiplicities feed SUM/COUNT directly WITHOUT materializing
        the cross product: the build side scatters to per-key
        [pair count, Σw, #valid-w] vectors, each probe row matmul-looks
        up its key's vector, and one standard SINGLE aggregation over
        the derived (probe-sized!) rows yields the exact result.
        DISTINCT-projections over a join (group-by, no aggregates) ride
        the same path. Returns None when the plan shape is ineligible;
        runtime ineligibility (sparse or over-span keys, over-memory
        build) falls back to the gather join + the normal aggregation
        over its output."""
        if getattr(self, "n_shards", None) is not None:
            return None     # dispatch-loop shards keep the gather path
        if node.step != AggStep.SINGLE:
            return None
        if not bool(self.session.get("mxu_join_enabled")):
            return None
        source = node.source
        proj = None
        if isinstance(source, ProjectNode) and all(
                isinstance(e, SymbolRef) for _, e in source.assignments):
            proj = source
            source = source.source
        if not isinstance(source, JoinNode):
            return None
        join = source
        if join.kind != JoinKind.INNER or len(join.criteria) != 1 \
                or join.filter is not None:
            return None
        pmap = {s.name: i for i, s in enumerate(join.left.outputs)}
        bmap = {s.name: i for i, s in enumerate(join.right.outputs)}
        rename = None if proj is None else \
            {s.name: e.name for s, e in proj.assignments}

        def resolve(name):
            if rename is not None:
                name = rename.get(name)
                if name is None:
                    return None
            if name in pmap:
                return ("p", pmap[name])
            if name in bmap:
                return ("b", bmap[name])
            return None

        group_chs = []
        for s in node.group_by:
            r = resolve(s.name)
            if r is None or r[0] != "p":
                return None     # group keys must be probe-side
            group_chs.append(r[1])
        ptypes = [s.type for s in join.left.outputs]
        btypes = [s.type for s in join.right.outputs]

        def num_kind(t):
            try:
                dt = np.dtype(T.to_numpy_dtype(t))
            except Exception:
                return None
            if dt.kind in ("i", "u"):
                return "i"
            if dt.kind == "f" and dt.itemsize == 8:
                return "f"
            return None

        vec_specs = [("cnt",)]

        def vec(spec):
            if spec not in vec_specs:
                vec_specs.append(spec)
            return vec_specs.index(spec)

        derive: List[tuple] = []
        helpers: List[int] = []
        out_types: List = []
        for out_sym, call in node.aggregations:
            if call.distinct or call.filter is not None \
                    or call.name not in self._MXU_FUSABLE_AGGS \
                    or len(call.args) > 1:
                return None
            if not call.args:
                if call.name != "count":
                    return None
                derive.append(("pairs",))
                out_types.append(out_sym.type)
                continue
            arg = call.args[0]
            if not isinstance(arg, SymbolRef):
                return None
            r = resolve(arg.name)
            if r is None:
                return None
            side, ch = r
            in_t = ptypes[ch] if side == "p" else btypes[ch]
            if call.name == "count":
                if side == "p":
                    derive.append(("cntp", ch))
                else:
                    derive.append(("cntb", vec(("validcnt", ch))))
            else:
                kind = num_kind(in_t)
                if kind is None or num_kind(out_sym.type) != kind:
                    return None
                if side == "p":
                    derive.append(("sump", ch, kind))
                else:
                    v = vec(("sum", ch, kind))
                    hvec = vec(("validcnt", ch))
                    if hvec not in helpers:
                        helpers.append(hvec)
                    derive.append(("sumb", v, kind,
                                   helpers.index(hvec)))
            out_types.append(out_sym.type)
        clause = join.criteria[0]
        if clause.left.name not in pmap or clause.right.name not in bmap:
            return None
        return PageStream(
            self._mxu_agg_join_run(
                node, join, proj, tuple(group_chs), tuple(derive),
                tuple(helpers), tuple(vec_specs), tuple(out_types),
                pmap[clause.left.name], bmap[clause.right.name]),
            node.outputs)

    def _mxu_agg_join_run(self, node, join, proj, group_chs, derive,
                          helpers, vec_specs, out_types, pkey_ch,
                          bkey_ch) -> Iterator[Page]:
        """Drive the fused aggregating join (see _mxu_agg_join): scatter
        the build vectors, matmul-lookup per probe page, feed ONE
        standard SINGLE aggregation over the derived rows, and restore
        output types/nullability in a post kernel. Keeps the gather
        path's robustness contracts: over-memory builds hand off to the
        streaming partitioned join, sparse/over-span keys fall back to
        the gather join + the normal aggregation over its output."""
        from trino_tpu.exec.jit_cache import profiled_kernel
        from trino_tpu.ops import join_mxu
        probe_stream = self.execute(join.left)
        build_stream = self.execute(join.right)
        build_iter = None
        if bool(self.session.get("spill_enabled")) \
                and int(self.session.get("spill_partition_count")) > 1:
            build_page, build_iter = \
                self._collect_build_resilient(build_stream)
        else:
            build_page = self._collect(build_stream)
        handed_off = False

        def gather_fallback(bp, bit=None):
            # the one gather-fallback shape, shared by every runtime
            # decline: _join_with_build OWNS the collected page / the
            # streaming iterator, then the normal aggregation runs over
            # its (re-projected) output
            jstream = self._join_with_build(
                join, probe_stream, join.right.outputs, bp, bit)
            if proj is not None:
                jstream = self._select_stream(jstream, proj)
            return self._agg_over_stream(node, jstream).iter_pages()

        try:
            if build_iter is not None:
                # mid-collect memory overflow: the streaming partitioned
                # hybrid join owns the pages; aggregate its output
                handed_off = True
                yield from gather_fallback(None, build_iter)
                return
            if build_page is None:
                if not node.group_by:
                    yield self._empty_global_agg(node, node.aggregations)
                return
            from trino_tpu.exec.memory import page_bytes
            if bool(self.session.get("spill_enabled")) and \
                    page_bytes(build_page) > int(self.session.get(
                        "join_spill_threshold_bytes")):
                # over-threshold build: keep the gather path's memory
                # discipline (spilled keys-on-device / partitioned
                # hybrid) instead of pinning the whole side for the
                # scatter — the fused matmul is not worth an OOM ladder
                # regression
                handed_off = True
                yield from gather_fallback(build_page)
                return
            bounds_op = cached_kernel(
                ("mxu-key-bounds", bkey_ch),
                lambda: join_mxu.key_bounds(bkey_ch))
            kmin_d, kmax_d = bounds_op(build_page)
            kmin, kmax = (int(x) for x in jax.device_get(
                [kmin_d, kmax_d]))
            span = kmax - kmin + 1 if kmax >= kmin else 0
            size = 1 << max((span - 1).bit_length(), 7) if span else 0
            table = None
            if 0 < span <= int(self.session.get("mxu_join_max_slots")) \
                    and build_page.capacity < join_mxu.MAX_EXACT_ROWS:
                table_op = profiled_kernel(
                    ("mxu-agg-table", bkey_ch, vec_specs, size),
                    lambda: join_mxu.scatter_agg_table(
                        size, vec_specs, bkey_ch))
                table, ndistinct_d, mag_ok_d = table_op(build_page,
                                                        kmin_d)
                ndistinct, mag_ok = jax.device_get(
                    [ndistinct_d, mag_ok_d])
                if not bool(mag_ok) or int(ndistinct) < span * float(
                        self.session.get("mxu_join_density_threshold")):
                    table = None
            if table is None:
                # sparse / over-span / magnitude-unsafe build keys:
                # the gather join + the normal aggregation
                handed_off = True
                yield from gather_fallback(build_page)
                return
            col = self.collector
            if col is not None:
                col.mxu_join()
            self._adaptive_span("join-mxu-agg", slots=size,
                                aggs=len(derive))
            # dynamic filtering, exactly like the gather join: the
            # build-side key range prefilters probe pages AND pushes
            # into connector file/row-group pruning (the scan's lazy
            # generator has not been pulled yet — build-before-probe)
            prefilter = None
            if self.session.get("enable_dynamic_filtering") and \
                    not T.is_string(join.left.outputs[pkey_ch].type):
                from trino_tpu.ops.join import (build_key_bounds,
                                                range_prefilter)
                b_op = cached_kernel(
                    ("dfbounds", bkey_ch),
                    lambda: build_key_bounds([bkey_ch]))
                pf_op = cached_kernel(
                    ("dfrange", pkey_ch),
                    lambda: range_prefilter(pkey_ch))
                prefilter = (pf_op, b_op(build_page))
                target = self._dyn_scan_target(
                    join.left, join.left.outputs[pkey_ch].name)
                if target is not None:
                    scan_node, col_name, col_type = target
                    lo_h, hi_h = jax.device_get(prefilter[1])
                    self.register_dynamic_domain(
                        scan_node, col_name, col_type,
                        lo_h.item(), hi_h.item())
            aligned = self._align_join_dictionaries(
                probe_stream, build_page, [pkey_ch], [bkey_ch])
            lookup_op = profiled_kernel(
                ("mxu-agg-lookup", pkey_ch, group_chs, derive, helpers,
                 size),
                lambda: join_mxu.agg_join_lookup(pkey_ch, group_chs,
                                                 derive, helpers))
            ncols = len(vec_specs)
            derived: List[Page] = []
            for page in self._coalesce_stream(
                    aligned, prefilter=prefilter).iter_pages():
                self._checkpoint()
                if col is not None:
                    col.add_mxu_flops(join_mxu.lookup_flops(
                        page.capacity, size, ncols))
                derived.append(lookup_op(page, table, kmin_d))
            merged, _rows = self.merge_counted_rows(derived)
            if merged is None:
                if not node.group_by:
                    yield self._empty_global_agg(node, node.aggregations)
                return
            nk = len(group_chs)

            def dtyp(d):
                if d[0] in ("pairs", "cntp", "cntb"):
                    return T.BIGINT
                return T.BIGINT if d[2] == "i" else T.DOUBLE

            spec_types = tuple(dtyp(d) for d in derive) \
                + (T.BIGINT,) * len(helpers)
            agg_specs = tuple(AggSpec("sum", nk + i, t)
                              for i, t in enumerate(spec_types))
            single_op = profiled_kernel(
                ("mxu-agg-single", nk, agg_specs),
                lambda: hash_aggregate(list(range(nk)), list(agg_specs),
                                       Step.SINGLE))
            post_op = cached_kernel(
                ("mxu-agg-post", nk, derive, len(helpers), out_types),
                lambda: join_mxu.agg_join_post(nk, derive, len(helpers),
                                               out_types))
            yield post_op(single_op(merged))
        finally:
            if not handed_off:
                self._free_collected(build_page)

    def _select_stream(self, stream: PageStream, proj) -> PageStream:
        """Apply a pure column-select/rename ProjectNode over a stream
        (the unwrap _mxu_agg_join performed, re-applied on its gather
        fallback so the aggregation sees its declared layout)."""
        lay = {s.name: i for i, s in enumerate(stream.symbols)}
        order = tuple(lay[e.name] for _, e in proj.assignments)
        return PageStream(
            stream.pages, tuple(s for s, _ in proj.assignments),
            stream.pending + ((("select", order),
                               lambda: lambda p, g, o=order: Page(
                                   tuple(p.columns[i] for i in o),
                                   p.num_rows), ()),))

    def _empty_global_agg(self, node: AggregationNode, specs) -> Page:
        cols = []
        for (sym, call), spec in zip(node.aggregations, specs):
            typ = sym.type
            if call.name in ("count", "count_if", "approx_distinct"):
                cols.append(Column(jnp.zeros(8, typ.dtype), None, typ, None))
            else:
                cols.append(Column(jnp.zeros(8, typ.dtype),
                                   jnp.zeros(8, dtype=jnp.bool_), typ, None))
        return Page(tuple(cols), 1)

    def _exec_GroupIdNode(self, node: GroupIdNode) -> PageStream:
        src = self.execute(node.source)
        lay, typ = _layout(src.symbols)
        out_syms = node.outputs
        all_group = tuple(dict.fromkeys(
            s for gs in node.grouping_sets for s in gs))

        def gen():
            for page in src.iter_pages():
                for set_idx, gset in enumerate(node.grouping_sets):
                    in_set = {s.name for s in gset}
                    cols = []
                    for sym in all_group + node.passthrough:
                        c = page.column(lay[sym.name])
                        if sym in all_group and sym.name not in in_set:
                            # null out keys excluded from this grouping set
                            c = Column(c.values,
                                       jnp.zeros(page.capacity, jnp.bool_),
                                       c.type, c.dictionary)
                        cols.append(c)
                    gid = Column(
                        jnp.full(page.capacity, set_idx, dtype=jnp.int64),
                        None, T.BIGINT, None)
                    cols.append(gid)
                    yield Page(tuple(cols), page.num_rows)
        return PageStream(gen(), out_syms)

    def _exec_SortNode(self, node: SortNode) -> PageStream:
        src = self.execute(node.source)
        lay, _ = _layout(src.symbols)
        keys = [SortKey(lay[o.symbol.name], o.ascending, o.nulls_first)
                for o in node.order_by]

        sort_op = cached_kernel(("sort", tuple(keys)),
                                lambda: order_by(keys))

        def gen():
            # sort spill (spiller/ + MergingSortedPages analog, re-thought):
            # over-budget inputs flush to host RANGE partitions of the
            # leading sort key (ties and NULLs can't straddle partitions —
            # exec/spill.py leading_rank), then each partition re-stages,
            # fully sorts, and emits in partition order == global order.
            from trino_tpu.exec.memory import page_bytes
            from trino_tpu.exec.spill import (partition_by_range,
                                              rank_bounds, leading_rank)
            threshold = int(self.session.get("sort_spill_threshold_bytes"))
            npart = int(self.session.get("spill_partition_count"))
            spillable = bool(self.session.get("spill_enabled")) and keys
            k0 = keys[0]
            store = None
            bounds = None
            part_op = None
            buf: List[Page] = []
            buf_bytes = 0

            def flush():
                nonlocal store, bounds, part_op, buf, buf_bytes
                self._fault_site("spill", "sort")
                merged = self.merge_counted(buf)
                buf, buf_bytes = [], 0
                if merged is None:
                    return
                self._record_spill(page_bytes(merged))
                if bounds is None:
                    store = self._new_spill_store(npart)
                    nf = k0.resolved_nulls_first()
                    rank_op = cached_kernel(
                        ("sort-spill-rank", k0.channel, k0.ascending, nf),
                        lambda: leading_rank(k0.channel, k0.ascending, nf))
                    bounds_op = cached_kernel(
                        ("sort-spill-bounds", npart),
                        lambda: rank_bounds(npart))
                    part_op = cached_kernel(
                        ("sort-spill-part", k0.channel, k0.ascending, nf,
                         npart),
                        lambda: partition_by_range(k0.channel, k0.ascending,
                                                   nf, npart))
                    bounds = bounds_op(rank_op(merged), merged.row_mask(),
                                       merged.num_rows)
                sorted_pg, counts = part_op(merged, bounds)
                store.spill_partitioned(sorted_pg, jax.device_get(counts))

            try:
                for page in src.iter_pages():
                    self._checkpoint()
                    buf.append(page)
                    buf_bytes += page_bytes(page)
                    if spillable and buf_bytes >= threshold:
                        flush()

                if store is None:
                    page = self.merge_counted(buf)
                    if page is None:
                        return
                    from trino_tpu.exec.memory import page_bytes as _pb
                    self.memory.reserve(_pb(page), "collect",
                                        device=self.mem_device)
                    try:
                        yield sort_op(page)
                    finally:
                        self._free_collected(page)
                    return
                if buf:
                    flush()
                for p in range(npart):
                    nrows = store.partition_rows(p)
                    if nrows == 0:
                        continue
                    pg = store.restage(p, _next_pow2(max(nrows, 1)))
                    store.drop(p)
                    yield sort_op(pg)
            finally:
                if store is not None:
                    store.close()
        return PageStream(gen(), src.symbols)

    def _exec_TopNNode(self, node: TopNNode) -> PageStream:
        src = self.execute(node.source)
        lay, _ = _layout(src.symbols)
        keys = tuple(SortKey(lay[o.symbol.name], o.ascending,
                             o.nulls_first) for o in node.order_by)
        # masked fixed-capacity kernel (ops/sort.top_n_masked): the count
        # rides as a runtime operand through the chain's param slots, so
        # the jit key is COUNT-FREE — LIMIT 5 and LIMIT 500 of one shape
        # dispatch the same warm executable, exactly like a hoisted
        # literal (the warmup-manifest contract for LIMIT k families)
        count = np.int32(node.count)
        key = ("topn-masked", keys)

        def builder():
            fn = top_n_masked(keys)
            return lambda page, g: fn(page, g[0])
        # per-page partial top-n fused with the upstream chain
        slot = self._slot(node)
        partial_topn = compose_chain(
            src.pending + ((key, builder, (count,),
                            None if slot is None
                            else DeviceShareSlot(slot)),))
        merge_kernel = cached_kernel(key, lambda: top_n_masked(keys),
                                     params=(count,))

        def gen():
            # partial top-n per page bounds the concat size at
            # count * n_pages (GroupedTopN-builder analog)
            partials = [partial_topn(page) for page in src.pages]
            if not partials:
                return
            merged = concat_pages(partials) if len(partials) > 1 \
                else partials[0]
            if int(merged.num_rows) == 0:
                return
            yield merge_kernel(merged, count)
        return PageStream(gen(), src.symbols)

    def _exec_JoinNode(self, node: JoinNode) -> PageStream:
        if node.kind == JoinKind.CROSS and not node.criteria:
            return self._exec_cross_join(node)
        if node.kind == JoinKind.RIGHT:
            # execute as LEFT with sides swapped, then restore column order
            # (the engine always probes with the preserved side; reference
            # reaches the same shape via LookupJoinOperatorFactory's
            # probe/build orientation)
            return self._exec_right_join(node)
        if node.kind == JoinKind.FULL:
            return self._exec_full_join(node)
        probe_stream = self.execute(node.left)
        build_stream = self.execute(node.right)
        # adaptive build collection (HashBuilderOperator's revoke-during-
        # build, re-thought): an INNER spillable build collects with
        # INCREMENTAL reservation — memory pressure mid-collect switches
        # to the streaming partitioned hybrid join (build pages partition
        # to host one at a time, never materialized whole), so an
        # underestimated build is a strategy switch, not an OOM cliff.
        # String keys ride the same handoff: the overflow path stages the
        # build host-side and rebases every page onto ONE union pool
        # before co-partitioning (_restage_string_build) — co-partition
        # hashing compares dictionary CODES, which only align under a
        # shared pool.
        build_iter = None
        if node.kind == JoinKind.INNER \
                and bool(self.session.get("spill_enabled")) \
                and int(self.session.get("spill_partition_count")) > 1:
            build_page, build_iter = \
                self._collect_build_resilient(build_stream)
        else:
            build_page = self._collect(build_stream)
        return self._join_with_build(node, probe_stream,
                                     build_stream.symbols, build_page,
                                     build_iter)

    def _join_with_build(self, node: JoinNode, probe_stream: PageStream,
                         build_symbols, build_page,
                         build_iter=None) -> PageStream:
        """INNER/LEFT equi-join over an already-collected build side
        (the body of _exec_JoinNode, split out so the MXU aggregating
        join's runtime fallback can hand its collected build to the
        gather path without re-executing the build subtree). Owns
        freeing the collected page."""
        probe_lay, probe_typ = _layout(probe_stream.symbols)
        build_lay, _ = _layout(build_symbols)
        probe_keys = [probe_lay[c.left.name] for c in node.criteria]
        build_keys = [build_lay[c.right.name] for c in node.criteria]
        # PruneJoinColumns: node.outputs may be a subset of left+right
        # (optimizer sets output_symbols) — emit only those channels, so
        # probe/build gathers skip dropped columns entirely
        out_symbols = node.outputs
        out_names = {s.name for s in out_symbols}
        probe_keep = tuple(i for i, s in enumerate(probe_stream.symbols)
                           if s.name in out_names)
        build_keep = tuple(i for i, s in enumerate(build_symbols)
                           if s.name in out_names)
        join_kind = JoinType.INNER if node.kind == JoinKind.INNER \
            else JoinType.LEFT

        # residual non-equi filter evaluated over joined layout — valid for
        # INNER only (LEFT would wrongly drop null-extended rows; planner
        # rejects such plans). Hoisted like chain predicates: the kernel
        # keys below carry the canonical tree, the values ride per call.
        post_pred = None
        post_params = ()
        if node.filter is not None:
            if join_kind != JoinType.INNER:
                raise ExecutionError(
                    "non-inner join with residual filter not supported")
            lay, typ = _layout(out_symbols)
            post_pred, post_params = self._hoist(
                lower_expr(node.filter, lay, typ))

        def join_op(cap: int, mode: str = "search"):
            def build():
                op = hash_join(probe_keys, build_keys, join_kind,
                               output_capacity=cap, prepared=True,
                               lookup=mode, probe_out=probe_keep,
                               build_out=build_keep)
                if post_pred is None:
                    return lambda p, b, g: op(p, b)
                post_filter = compile_filter(post_pred)

                def run(p, b, g):
                    out, total = op(p, b)
                    return out.filter(post_filter(out, g)), total
                return run
            kernel = cached_kernel(
                ("join", tuple(probe_keys), tuple(build_keys), join_kind,
                 cap, post_pred, mode, probe_keep, build_keep), build,
                params=post_params)
            return lambda p, b: kernel(p, b, post_params)

        n_probe_cols = len(probe_keep)

        def unique_ops(mode: str):
            probe_op = cached_kernel(
                ("uprobe", tuple(probe_keys), tuple(build_keys), mode,
                 probe_keep),
                lambda: unique_inner_probe(probe_keys, build_keys,
                                           lookup=mode,
                                           probe_out=probe_keep))

            def build_attach():
                from trino_tpu.ops.join import attach_build
                at = attach_build(n_probe_cols, build_out=build_keep)
                fn = None if post_pred is None else compile_filter(post_pred)

                def run(pre, prepared, g):
                    out = at(pre, prepared)
                    if fn is not None:
                        out = out.filter(fn(out, g))
                    return out
                return run
            attach_kernel = cached_kernel(
                ("uattach", n_probe_cols, post_pred, build_keep),
                build_attach, params=post_params)
            attach_op = lambda pre, prepared: attach_kernel(  # noqa: E731
                pre, prepared, post_params)
            return probe_op, attach_op

        def gen():
            if build_iter is not None:
                # the build overflowed its reservation mid-collect: the
                # streaming partitioned hybrid consumes the remaining
                # pages without ever materializing the whole side
                node_id = ("join",
                           tuple(c.left.name for c in node.criteria),
                           tuple(c.right.name for c in node.criteria))
                if any(T.is_string(build_symbols[bk].type)
                       for bk in build_keys):
                    # string keys: stage the build host-side and rebase
                    # every page onto ONE union pool first — the
                    # co-partition hash and the per-partition kernels
                    # compare dictionary CODES, so both sides must share
                    # a pool before any partitioning happens. The probe
                    # then re-encodes onto that union pool exactly like
                    # the collected path's dictionary alignment (INNER
                    # only, which the overflow gates guarantee).
                    stage, pools = self._restage_string_build(
                        build_iter, build_keys)
                    if stage is None:
                        return      # empty build, INNER: no output rows
                    try:
                        aligned = self._align_probe_to_pools(
                            probe_stream,
                            {pk: pools[bk]
                             for pk, bk in zip(probe_keys, build_keys)
                             if bk in pools})
                        replay = stage.drain_partition_chunks(
                            0, stage.chunk_rows_for(0, self._spill_budget(
                                int(self.session.get(
                                    "join_spill_threshold_bytes")))))
                        yield from self._run_partitioned_inner(
                            aligned, replay, probe_keys, build_keys,
                            join_op, node_id=node_id)
                    finally:
                        stage.close()
                    return
                yield from self._run_partitioned_inner(
                    probe_stream, build_iter, probe_keys, build_keys,
                    join_op, node_id=node_id)
                return
            collected = build_page   # only the _collect'ed page was reserved
            bp = build_page
            if bp is None:
                if join_kind == JoinType.INNER:
                    return
                # LEFT join with empty build: emit null-extended probe rows
                bp = self._null_build_page(node.right.outputs)
            # INNER only: the sentinel codes for probe values absent from
            # the build pool are filtered out by inner semantics before
            # any decode; LEFT would emit them (out-of-pool codes in the
            # output), so mismatched-dictionary LEFT keys stay fail-loud
            # in the kernels
            aligned = probe_stream
            if join_kind == JoinType.INNER:
                aligned = self._align_join_dictionaries(
                    probe_stream, bp, probe_keys, build_keys)
            from trino_tpu.exec.memory import page_bytes
            if join_kind == JoinType.INNER and build_page is not None and \
                    self.session.get("spill_enabled") and \
                    page_bytes(build_page) > int(self.session.get(
                        "join_spill_threshold_bytes")):
                yield from self._run_spilled_inner(
                    aligned, build_page, probe_keys, build_keys,
                    post_pred, post_params, probe_keep, build_keep,
                    join_op,
                    skew_hint=getattr(node, "build_skew_estimate", None),
                    node_id=("join",
                             tuple(c.left.name for c in node.criteria),
                             tuple(c.right.name for c in node.criteria)))
                return
            try:
                prepared, max_run, mode = self._prepare_probe(
                    build_keys, bp,
                    mxu_ok=(join_kind == JoinType.INNER
                            and len(build_keys) == 1))
                prefilter = None
                if join_kind == JoinType.INNER and \
                        self.session.get("enable_dynamic_filtering") and \
                        not T.is_string(
                            probe_stream.symbols[probe_keys[0]].type):
                    # dynamic filtering: build-side key range -> probe-side
                    # scan prefilter (first join key bounds any composite)
                    from trino_tpu.ops.join import (build_key_bounds,
                                                    range_prefilter)
                    bounds_op = cached_kernel(
                        ("dfbounds", build_keys[0]),
                        lambda: build_key_bounds(build_keys))
                    pf_op = cached_kernel(
                        ("dfrange", probe_keys[0]),
                        lambda: range_prefilter(probe_keys[0]))
                    prefilter = (pf_op, bounds_op(bp))
                    # the same build-side range, pushed into connector
                    # FILE/ROW-GROUP pruning when the probe key maps
                    # straight to a zone-mapped scan column (the lake's
                    # dynamic-filter pushdown) — the scan's generator
                    # has not been pulled yet (build-before-probe), so
                    # the domain lands before splits are chosen
                    target = self._dyn_scan_target(
                        node.left,
                        probe_stream.symbols[probe_keys[0]].name)
                    if target is not None:
                        scan_node, col_name, col_type = target
                        lo_h, hi_h = jax.device_get(prefilter[1])
                        self.register_dynamic_domain(
                            scan_node, col_name, col_type,
                            lo_h.item(), hi_h.item())
                coalesced = self._coalesce_stream(aligned,
                                                  prefilter=prefilter)
                probe_in = coalesced
                if mode == "mxu":
                    probe_in = self._mxu_stream(
                        coalesced, prepared[10].shape[0])
                if join_kind == JoinType.INNER and max_run <= 1:
                    # unique build side (primary/dimension key): the
                    # no-expansion probe + live-size build attach
                    probe_op, attach_op = unique_ops(mode)
                    yield from self._run_unique_inner(
                        probe_in, prepared, probe_op, attach_op)
                else:
                    yield from _run_with_overflow(
                        probe_in, prepared,
                        lambda cap: join_op(cap, mode),
                        self.page_capacity)
            finally:
                self._free_collected(collected)
        return PageStream(gen(), out_symbols)

    def _run_spilled_inner(self, probe_stream, build_page,
                           probe_keys, build_keys, post_pred, post_params,
                           probe_keep, build_keep,
                           fallback_join_op, skew_hint=None,
                           node_id=None) -> Iterator[Page]:
        """Spill-mode INNER join (HashBuilderOperator spill states +
        SpillingJoinProcessor analog): sort the build keys on device, move
        the build's payload columns to HOST RAM, keep only (sorted keys,
        permutation) in HBM (~12B/row), probe streams against the key
        array, and gather build columns host-side at match count.

        Duplicate-key and string-keyed builds — the shapes the unique
        key-array probe cannot serve — route to the robust dynamic
        HYBRID partitioned join (`_run_partitioned_inner`): both sides
        hash-partition to host, partitions join in memory, over-budget
        partitions recursively repartition, heavy keys split out. The
        CBO's `build_skew_estimate` (> 2 expected duplicates per key)
        pre-routes there without paying a wasted unique-prep; the
        runtime observation still decides when the estimate is absent
        or wrong."""
        from trino_tpu.exec.memory import page_bytes
        from trino_tpu.ops.join import (attach_build_host,
                                        build_dense_table_rows,
                                        prepare_build_spilled,
                                        spilled_dense_probe,
                                        spilled_unique_probe)
        self._fault_site("spill", "join-build")
        npart = int(self.session.get("spill_partition_count"))
        partitioned_ok = npart > 1
        # varchar join keys compare by per-dictionary code — the spilled
        # probe never sees the build dictionaries, so it cannot apply the
        # shared-dictionary guard the in-memory kernels enforce; the
        # partitioned path restages full pages (dictionaries ride along
        # in store meta) and runs the verifying in-memory kernels per
        # partition, so string keys go there too
        string_keyed = any(
            build_page.columns[bk].dictionary is not None
            for bk in build_keys)
        is_unique = False
        cbo_partitioned = (partitioned_ok and skew_hint is not None
                           and skew_hint > 2.0)
        if not string_keyed and not cbo_partitioned:
            try:
                prep = cached_kernel(
                    ("spill-prep", tuple(build_keys)),
                    lambda: prepare_build_spilled(build_keys))
                (bkey_s, bperm, n_live, n_rows_d, has_null, is_unique_d,
                 kmin_d, kmax_d) = prep(build_page)
                # ONE batched round trip for all four scalars (~95ms each
                # through the tunnel)
                uq, nr, km, kx = jax.device_get(
                    [is_unique_d, n_rows_d, kmin_d, kmax_d])
                is_unique, n_rows, kmin, kmax = \
                    bool(uq), int(nr), int(km), int(kx)
            except Exception:
                self._free_collected(build_page)
                raise
        if string_keyed or cbo_partitioned or not is_unique:
            if partitioned_ok:
                yield from self._run_partitioned_inner(
                    probe_stream, build_page, probe_keys, build_keys,
                    fallback_join_op, node_id=node_id)
                return
            # partitioning disabled (spill_partition_count <= 1):
            # legacy in-memory expansion join
            try:
                prepared, _max_run, dense = self._prepare_with_dense(
                    build_keys, build_page)
                yield from _run_with_overflow(
                    self._coalesce_stream(probe_stream), prepared,
                    lambda cap: fallback_join_op(
                        cap, "dense" if dense else "search"),
                    self.page_capacity)
            finally:
                self._free_collected(build_page)
            return
        # pruned layouts: the pre page carries kept probe cols (plus
        # verify-only key cols for composite keys, dropped after attach);
        # only kept build cols move to host for emission, key cols ride
        # along host-side when composite verification needs them
        composite = len(probe_keys) > 1
        probe_out = list(probe_keep)
        extra_p = [k for k in probe_keys if k not in probe_out] \
            if composite else []
        probe_out_full = tuple(probe_out + extra_p)
        n_pre_cols = len(probe_out_full)
        host_idx = list(build_keep) + \
            ([k for k in build_keys if k not in build_keep]
             if composite else [])
        emit = tuple(range(len(build_keep)))
        verify = None
        if composite:
            verify = [(probe_out_full.index(pk), host_idx.index(bk))
                      for pk, bk in zip(probe_keys, build_keys)]
        # move payload columns to host CHUNK-WISE (round 15, the PR 10
        # leftover): the old whole-build device_get sliced every column
        # up front, transiently materializing a second copy of a build
        # that is over the spill threshold BY DEFINITION — at exactly
        # the moment HBM is scarce. Each chunk's device slice is now
        # the only transient, reserved against the ledger while it
        # transfers.
        try:
            host_cols = [
                self._stage_column_host(build_page.columns[ci], n_rows)
                for ci in host_idx]
        except Exception:
            self._free_collected(build_page)
            raise
        self._record_spill(sum(
            v.nbytes + (m.nbytes if m is not None else 0)
            for v, m, _, _ in host_cols))
        self._free_collected(build_page)
        # dense spilled builds (surrogate keys, the common >threshold
        # case): ONE int32 row table on device — ~4B/slot instead of
        # 12B/row, and probes are one gather instead of anchored search
        span = kmax - kmin + 1 if kmax >= kmin else 0
        spill_dense = 0 < span <= (1 << 28)
        if spill_dense:
            size = _next_pow2(span)
            tab_op = cached_kernel(("dense-table-rows", size),
                                   lambda: build_dense_table_rows(size))
            table = tab_op(bkey_s, bperm, n_live, kmin)
            kmin_dev = jnp.uint64(kmin)
            bkey_s = bperm = None   # free sorted keys + permutation
            held_bytes = int(table.nbytes)
            probe_op = cached_kernel(
                ("spill-probe-dense", tuple(probe_keys), probe_out_full),
                lambda: spilled_dense_probe(probe_keys,
                                            probe_out=probe_out_full))
        else:
            held_bytes = int(bkey_s.nbytes + bperm.nbytes)
            probe_op = cached_kernel(
                ("spill-probe", tuple(probe_keys), probe_out_full),
                lambda: spilled_unique_probe(probe_keys,
                                             probe_out=probe_out_full))
        self.memory.reserve(held_bytes, "join-spill-keys",
                            device=self.mem_device)
        post_filter = None if post_pred is None else \
            compile_filter(post_pred)   # called with post_params below
        drop_extra = None
        if extra_p:
            drop_extra = tuple(range(len(probe_keep))) + tuple(
                range(n_pre_cols, n_pre_cols + len(build_keep)))
        try:
            it2 = probe_stream if isinstance(probe_stream, Iterator) \
                else self._coalesce_stream(probe_stream).iter_pages()
            for batch in _byte_bounded_batches(it2, 1 << 29):
                if spill_dense:
                    results = [probe_op(p, table, kmin_dev) for p in batch]
                else:
                    results = [probe_op(p, bkey_s, bperm, n_live)
                               for p in batch]
                fetched = jax.device_get(
                    [(t, pre.num_rows) for pre, _, t in results])
                for (pre, found, _), (total, live) in zip(results, fetched):
                    total, live = int(total), int(live)
                    if total == 0:
                        continue
                    pre = self._compact_probe(pre, found, total, live)
                    pre = self._tight(pre, total)
                    out = attach_build_host(pre, n_pre_cols, host_cols,
                                            verify=verify, emit=emit)
                    if drop_extra is not None:
                        out = out.select_columns(drop_extra)
                    if post_filter is not None:
                        out = out.filter(post_filter(out, post_params))
                    yield out
        finally:
            self.memory.free(held_bytes, "join-spill-keys",
                             device=self.mem_device)

    # device-transient budget for staging one spilled-build column chunk
    _SPILL_STAGE_CHUNK_BYTES = 128 << 20

    def _stage_column_host(self, c, n_rows: int):
        """One build payload column staged to host numpy in BOUNDED
        chunks: the device transient is a single chunk's slice (reserved
        against the query ledger for the duration of its transfer), not
        a full second copy of the column. Returns the
        (values, valid, type, dictionary) tuple attach_build_host
        consumes."""
        n = max(n_rows, 1)
        width = int(np.dtype(c.values.dtype).itemsize) \
            + (1 if c.valid is not None else 0)
        chunk = max(1 << 16, self._SPILL_STAGE_CHUNK_BYTES
                    // max(width, 1))
        vals = np.empty(n, dtype=np.dtype(c.values.dtype))
        valid = None if c.valid is None else np.empty(n, dtype=bool)
        off = 0
        while off < n:
            hi = min(off + chunk, n)
            held = (hi - off) * width
            self.memory.reserve(held, "spill-stage",
                                device=self.mem_device)
            try:
                self._checkpoint()
                vals[off:hi] = np.asarray(jax.device_get(
                    c.values[off:hi]))
                if valid is not None:
                    valid[off:hi] = np.asarray(jax.device_get(
                        c.valid[off:hi]))
            finally:
                self.memory.free(held, "spill-stage",
                                 device=self.mem_device)
            off = hi
        return vals, valid, c.type, c.dictionary

    def _collect_build_resilient(self, stream: PageStream):
        """Collect a join build side with INCREMENTAL reservation: each
        page reserves before the next materializes, so memory pressure
        surfaces mid-collect — where it is a STRATEGY SWITCH (return the
        pages-so-far chained with the rest of the stream for the
        streaming partitioned join) instead of a terminal OOM after the
        whole side sat in HBM. Returns (page, None) when the build fit
        (classic paths, reservation swapped to the merged page) or
        (None, iterator) on pressure; (None, None) = empty build."""
        from trino_tpu.exec.memory import (ClusterOutOfMemoryError,
                                           ExceededMemoryLimitError,
                                           page_bytes)
        self._fault_site("memory", "collect")
        pages: List[Page] = []
        held = 0
        it = stream.iter_pages()
        try:
            for page in it:
                self._checkpoint()
                b = page_bytes(page)
                try:
                    self.memory.reserve(b, "collect",
                                        device=self.mem_device)
                except (ExceededMemoryLimitError,
                        ClusterOutOfMemoryError):
                    # hand every held byte back (a killer victim's
                    # release) and clear a self-kill mark: the pressure
                    # is relieved by NOT materializing this build
                    self.memory.free(held, "collect",
                                     device=self.mem_device)
                    self.memory.clear_kill()
                    self._adaptive_span("join-build-overflow",
                                        held_bytes=held + b)
                    pages.append(page)
                    return None, _drain_then(pages, it)
                held += b
                pages.append(page)
        except BaseException:
            self.memory.free(held, "collect", device=self.mem_device)
            raise
        merged = self.merge_counted(pages)
        # swap the per-page reservations for the merged page's bytes
        # (merge shrinks to the live pow2): free FIRST — holding both
        # transiently would double-reserve and trip a limit the merged
        # page alone fits under
        self.memory.free(held, "collect", device=self.mem_device)
        if merged is None:
            return None, None
        try:
            self.memory.reserve(page_bytes(merged), "collect",
                                device=self.mem_device)
        except (ExceededMemoryLimitError, ClusterOutOfMemoryError):
            # even the merged page is over the line: degrade with it as
            # the (single-page) streaming build
            self.memory.clear_kill()
            self._adaptive_span("join-build-overflow",
                                held_bytes=page_bytes(merged))
            return None, iter([merged])
        return merged, None

    def _spill_budget(self, threshold: int) -> int:
        """The per-partition device budget for restaging/recursion
        decisions: the configured spill threshold, shrunk under an
        active memory limit so a restaged partition's reservation can
        always be granted (a budget above the limit would turn the
        ladder's graceful degradation back into a reservation
        failure)."""
        budget = int(threshold)
        limit = getattr(self.memory, "limit", None)
        if limit:
            budget = min(budget, max(int(limit) // 4, 1 << 16))
        pool = getattr(self.memory, "pool", None)
        if pool is not None and pool.limit:
            budget = min(budget, max(int(pool.limit) // 4, 1 << 16))
        return max(budget, 1)

    def _run_partitioned_inner(self, probe_stream, build_source,
                               probe_keys, build_keys, join_op,
                               node_id=None) -> Iterator[Page]:
        """Robust dynamic hybrid hash join for duplicate-key / skewed /
        string-keyed over-threshold builds (the shapes that previously
        fell back to an UNBOUNDED in-memory build): both sides
        hash-partition into host stores with one device partition-sort
        each, then every co-partition joins with the normal in-memory
        kernels when its build fits the spill budget — and degrades
        gracefully when it doesn't (`_join_partitions`: salted recursive
        repartition, heavy-key splitting, bounded chunked-build
        fallback). No cliff: device footprint is bounded by one
        partition's build plus one probe chunk at every depth."""
        from trino_tpu.exec.memory import page_bytes
        from trino_tpu.exec.spill import partition_by_hash
        npart = int(self.session.get("spill_partition_count"))
        threshold = self._spill_budget(
            int(self.session.get("join_spill_threshold_bytes")))
        bkeys_t, pkeys_t = tuple(build_keys), tuple(probe_keys)
        build_is_page = isinstance(build_source, Page)

        def part_op(keys, salt):
            return cached_kernel(
                ("join-spill-part", keys, npart, salt),
                lambda: partition_by_hash(keys, npart, salt=salt))

        try:
            bstore = self._new_spill_store(npart)
            pstore = self._new_spill_store(npart)
        except BaseException:
            if build_is_page:
                self._free_collected(build_source)
            raise
        try:
            self._fault_site("spill", "join-part")
            bop = part_op(bkeys_t, 0)
            if build_is_page:
                self._record_spill(page_bytes(build_source))
                try:
                    sorted_pg, counts = bop(build_source)
                    bstore.spill_partitioned(sorted_pg,
                                             jax.device_get(counts))
                finally:
                    self._free_collected(build_source)
            else:
                # streaming build (mid-collect overflow handoff): pages
                # partition to host one at a time — the whole side is
                # never resident on device
                for bpage in build_source:
                    self._checkpoint()
                    sorted_pg, counts = bop(bpage)
                    bstore.spill_partitioned(sorted_pg,
                                             jax.device_get(counts))
                self._record_spill(bstore.bytes)
            it = probe_stream if isinstance(probe_stream, Iterator) \
                else self._coalesce_stream(probe_stream).iter_pages()
            pop = part_op(pkeys_t, 0)
            for page in it:
                self._checkpoint()
                sorted_pg, counts = pop(page)
                pstore.spill_partitioned(sorted_pg,
                                         jax.device_get(counts))
            self._record_spill(pstore.bytes)
            yield from self._join_partitions(
                bstore, pstore, 0, bkeys_t, pkeys_t, join_op, part_op,
                threshold, node_id)
        finally:
            bstore.close()
            pstore.close()

    def _join_partitions(self, bstore, pstore, depth: int, bkeys, pkeys,
                         join_op, part_op, threshold: int,
                         node_id=None) -> Iterator[Page]:
        """One round of the hybrid join over co-partitioned stores. Per
        partition, in order: in-budget -> in-memory join; heavy build
        keys (unsplittable by ANY re-hash) -> split both sides out into
        the dedicated chunked-build pass (the replicate/spread analog of
        parallel/exchange's JSPIM handling: build chunks replicate, the
        probe partition streams — spreads — through each); still over
        budget -> recursive salted repartition of BOTH sides up to
        `spill_max_recursion`; at max depth -> bounded chunked-build
        fallback. Every switch counts and spans."""
        from trino_tpu.exec.spill import (detect_partition_heavy_keys,
                                          partition_key_hashes,
                                          split_partition)
        max_rec = int(self.session.get("spill_max_recursion"))
        heavy_limit = int(self.session.get("spill_heavy_key_limit"))
        npart = bstore.npart
        for p in range(npart):
            self._checkpoint()
            brows = bstore.partition_rows(p)
            prows = pstore.partition_rows(p)
            if brows == 0 or prows == 0:
                bstore.drop(p)
                pstore.drop(p)
                continue
            if bstore.partition_bytes(p) <= max(threshold, 1):
                yield from self._join_one_partition(
                    bstore, pstore, p, bkeys, join_op, threshold)
                continue
            if heavy_limit > 0 and depth < max_rec and npart > 1:
                bhashes = partition_key_hashes(bstore, p, bkeys)
                heavy = detect_partition_heavy_keys(
                    bstore, p, bkeys, heavy_limit,
                    max(2, brows // (2 * max(npart, 2))),
                    piece_hashes=bhashes)
                if len(heavy):
                    self._fault_site("spill", "join-heavy")
                    self._adaptive_event("heavy_key_splits")
                    self._adaptive_span("join-heavy-split", depth=depth,
                                        keys=int(len(heavy)))
                    if self.adaptive is not None and node_id is not None:
                        self.adaptive.record_join_heavy(node_id, heavy)
                    hb = split_partition(bstore, p, bkeys, heavy,
                                         piece_hashes=bhashes)
                    hp = split_partition(pstore, p, pkeys, heavy)
                    try:
                        yield from self._join_chunked_build(
                            hb, hp, 0, bkeys, join_op, threshold)
                    finally:
                        hb.close()
                        hp.close()
                    if bstore.partition_rows(p) == 0 or \
                            pstore.partition_rows(p) == 0:
                        bstore.drop(p)
                        pstore.drop(p)
                        continue
                    if bstore.partition_bytes(p) <= max(threshold, 1):
                        yield from self._join_one_partition(
                            bstore, pstore, p, bkeys, join_op, threshold)
                        continue
            if depth >= max_rec or npart <= 1:
                self._fault_site("spill", "join-fallback")
                self._adaptive_event("spill_fallbacks")
                self._adaptive_span("join-spill-fallback", depth=depth)
                yield from self._join_chunked_build(
                    bstore, pstore, p, bkeys, join_op, threshold)
                continue
            self._fault_site("spill", "join-recurse")
            self._adaptive_event("join_recursions")
            self._adaptive_span("join-spill-recurse", depth=depth + 1)
            childb = self._new_spill_store(npart)
            childp = self._new_spill_store(npart)
            try:
                bop = part_op(bkeys, depth + 1)
                # drain both transfers: the recursion must never hold
                # parent AND child copies of one side against the budget
                for chunk in bstore.drain_partition_chunks(
                        p, bstore.chunk_rows_for(p, threshold)):
                    self._checkpoint()
                    spg, cnt = bop(chunk)
                    childb.spill_partitioned(spg, jax.device_get(cnt))
                bstore.drop(p)
                pop = part_op(pkeys, depth + 1)
                for chunk in pstore.drain_partition_chunks(
                        p, pstore.chunk_rows_for(p, threshold)):
                    self._checkpoint()
                    spg, cnt = pop(chunk)
                    childp.spill_partitioned(spg, jax.device_get(cnt))
                pstore.drop(p)
                yield from self._join_partitions(
                    childb, childp, depth + 1, bkeys, pkeys, join_op,
                    part_op, threshold, node_id)
            finally:
                childb.close()
                childp.close()

    def _join_one_partition(self, bstore, pstore, p: int, bkeys,
                            join_op, threshold: int) -> Iterator[Page]:
        """In-memory join of one co-partition: restage the build side
        (reserved against the query ledger), prepare once, stream the
        probe partition through in bounded chunks."""
        from trino_tpu.exec.memory import page_bytes
        nrows = bstore.partition_rows(p)
        bpage = bstore.restage(p, _next_pow2(max(nrows, 1)))
        bstore.drop(p)
        held = page_bytes(bpage)
        self.memory.reserve(held, "join-part-build",
                            device=self.mem_device)
        try:
            prepared, _max_run, dense = self._prepare_with_dense(
                list(bkeys), bpage)
            yield from _run_with_overflow(
                pstore.drain_partition_chunks(
                    p, pstore.chunk_rows_for(p, threshold)),
                prepared,
                lambda cap: join_op(cap, "dense" if dense else "search"),
                self.page_capacity)
            pstore.drop(p)
        finally:
            self.memory.free(held, "join-part-build",
                             device=self.mem_device)

    def _join_chunked_build(self, bstore, pstore, p: int, bkeys,
                            join_op, threshold: int) -> Iterator[Page]:
        """Bounded chunked-build join: INNER join distributes over
        DISJOINT build chunks (each probe row meets each of its key's
        build rows in exactly one chunk), so joining the probe partition
        against budget-sized build chunks is correct at ANY build size —
        the bounded-memory floor under both the heavy-key path and the
        max-recursion fallback. More passes, never more memory."""
        from trino_tpu.exec.memory import page_bytes
        pchunk_rows = pstore.chunk_rows_for(p, threshold)
        # build chunks drain (single pass); the probe partition must
        # stay resident — it re-streams once per build chunk
        for bchunk in bstore.drain_partition_chunks(
                p, bstore.chunk_rows_for(p, threshold)):
            self._checkpoint()
            held = page_bytes(bchunk)
            self.memory.reserve(held, "join-chunk-build",
                                device=self.mem_device)
            try:
                prepared, _mr, dense = self._prepare_with_dense(
                    list(bkeys), bchunk)
                yield from _run_with_overflow(
                    pstore.iter_partition_chunks(p, pchunk_rows),
                    prepared,
                    lambda cap, m=("dense" if dense else "search"):
                        join_op(cap, m),
                    self.page_capacity)
            finally:
                self.memory.free(held, "join-chunk-build",
                                 device=self.mem_device)
        bstore.drop(p)
        pstore.drop(p)

    def _compact_probe(self, pre: Page, found, total: int,
                       live: int) -> Page:
        """Compact a probe result to its matched rows — SKIPPED when every
        live row matched (fact-to-dim joins after dynamic filtering often
        match ~100%; the compaction stable-sort is the single biggest
        per-buffer cost once the lookup itself is a dense gather)."""
        if total == live:
            return pre
        op = cached_kernel(("probe-compact",),
                           lambda: lambda p, f: p.filter(f))
        return op(pre, found)

    def _run_unique_inner(self, probe_stream, prepared, probe_op,
                          attach_op) -> Iterator[Page]:
        """Drive the unique-build INNER fast path: gather-probe kernel per
        page, batched count fetch, compact ONLY partially-matching buffers,
        shrink to live size, THEN gather build columns — so the attach
        gathers run at match count, not probe capacity. No overflow loop:
        output rows <= probe rows always."""
        it = probe_stream if isinstance(probe_stream, Iterator) \
            else probe_stream.iter_pages()
        for batch in _byte_bounded_batches(it, 1 << 29):
            results = [probe_op(page, prepared) for page in batch]
            fetched = jax.device_get(
                [(t, pre.num_rows) for pre, _, t in results])
            for (pre, found, _), (total, live) in zip(results, fetched):
                total, live = int(total), int(live)
                if total == 0:
                    continue
                out = self._compact_probe(pre, found, total, live)
                yield attach_op(self._tight(out, total), prepared)

    def _align_join_dictionaries(self, probe_stream: PageStream,
                                 build_page: Page, probe_keys,
                                 build_keys) -> PageStream:
        """String join keys across DISTINCT dictionaries: remap probe key
        codes onto the build side's pool (DictionaryBlock re-encode; the
        kernels compare codes, so both sides must share one pool)."""
        return self._align_probe_to_pools(
            probe_stream,
            {pk: build_page.columns[bk].dictionary
             for pk, bk in zip(probe_keys, build_keys)
             if build_page.columns[bk].dictionary is not None})

    def _restage_string_build(self, build_source, build_keys):
        """Overflow handoff for STRING-keyed builds (closes the gap the
        streaming partitioned join carried since it landed): pages of a
        streaming build may encode the same key column against DISTINCT
        pools (per-source dictionaries under a union, re-created memory
        tables), and co-partition hashing compares CODES — so the whole
        build stages host-side FIRST (single-partition store: one device
        compaction per page, the side is never resident whole), then
        every dictionary column whose pieces span more than one pool is
        rebased onto the union pool with a host-side int32 code remap
        (DictionaryBlock 'compact to shared pool', applied at rest).

        Returns (stage, {build_channel: dictionary}) — the caller drains
        partition 0 as the replay build source, aligns the probe to the
        returned pools BEFORE co-partitioning, and owns stage.close().
        (None, {}) = empty build."""
        from trino_tpu.exec.spill import partition_by_hash
        from trino_tpu.page import union_dictionaries
        bkeys_t = tuple(build_keys)
        compact = cached_kernel(
            ("join-spill-part", bkeys_t, 1, 0),
            lambda: partition_by_hash(bkeys_t, 1, salt=0))
        stage = self._new_spill_store(1)
        try:
            piece_dicts: List[list] = []
            for page in build_source:
                self._checkpoint()
                self._fault_site("spill", "join-string-stage")
                sorted_pg, counts = compact(page)
                before = len(stage.pieces[0])
                stage.spill_partitioned(sorted_pg,
                                        jax.device_get(counts))
                if len(stage.pieces[0]) > before:
                    # dictionaries per APPENDED piece (all-pad pages
                    # append nothing) — stage.meta only remembers the
                    # first page's pools
                    piece_dicts.append(
                        [c.dictionary for c in page.columns])
            self._record_spill(stage.bytes)
            if stage.meta is None:
                stage.close()
                return None, {}
            for ci in range(len(stage.meta)):
                dicts = [pd[ci] for pd in piece_dicts]
                if dicts[0] is None:
                    continue
                uniq: List = []
                for d in dicts:
                    if not any(d is u or d.fingerprint == u.fingerprint
                               for u in uniq):
                        uniq.append(d)
                final = uniq[0]
                if len(uniq) > 1:
                    self._adaptive_span("join-string-pool-union",
                                        channel=ci, pools=len(uniq))
                    union, remaps = union_dictionaries(uniq)
                    by_fp = {u.fingerprint: np.asarray(r)
                             for u, r in zip(uniq, remaps)}
                    for piece, d in zip(stage.pieces[0], dicts):
                        tbl = by_fp[d.fingerprint]
                        vals = piece[ci][0]
                        # padding/null codes (< 0) pass through; live
                        # codes remap. int32 -> int32: the store's byte
                        # accounting is unchanged by the rewrite.
                        piece[ci] = (np.where(
                            vals >= 0,
                            tbl[np.clip(vals, 0, len(tbl) - 1)],
                            vals).astype(vals.dtype), piece[ci][1])
                    final = union
                typ, _ = stage.meta[ci]
                stage.meta[ci] = (typ, final)
            pools = {bk: stage.meta[bk][1] for bk in bkeys_t
                     if stage.meta[bk][1] is not None}
            return stage, pools
        except BaseException:
            stage.close()
            raise

    def _align_probe_to_pools(self, probe_stream: PageStream, pools
                              ) -> PageStream:
        """Re-encode probe key channels onto given build-side pools
        (`pools`: {probe_channel: build Dictionary}). Probe values absent
        from the build pool map to unique sentinels past the pool end —
        they can never match (INNER-only discipline; LEFT keeps the
        fail-loud kernels). Lazy: tables build on the first page per
        (probe-dict, channel) pair."""
        pools = {pk: bd for pk, bd in pools.items() if bd is not None}
        if not pools:
            return probe_stream
        maps: Dict[tuple, jnp.ndarray] = {}

        def gen():
            for page in probe_stream.iter_pages():
                cols = list(page.columns)
                changed = False
                for pk, bd in pools.items():
                    pc = cols[pk]
                    if pc.dictionary is None or pc.dictionary is bd:
                        continue
                    key = (id(pc.dictionary), pk)
                    tbl = maps.get(key)
                    if tbl is None:
                        pvals = pc.dictionary.values
                        n_b = len(bd.values)
                        if n_b:
                            codes = np.minimum(
                                np.searchsorted(bd.values, pvals),
                                n_b - 1).astype(np.int64)
                            present = bd.values[codes] == pvals
                        else:
                            codes = np.zeros(len(pvals), np.int64)
                            present = np.zeros(len(pvals), bool)
                        out = np.where(
                            present, codes,
                            n_b + np.arange(len(pvals), dtype=np.int64))
                        tbl = maps[key] = jnp.asarray(
                            out.astype(np.int32))
                    cols[pk] = Column(
                        jnp.take(tbl, jnp.clip(pc.values, 0),
                                 mode="clip"),
                        pc.valid, pc.type, bd)
                    changed = True
                yield Page(tuple(cols), page.num_rows) if changed else page
        return PageStream(gen(), probe_stream.symbols)

    def _prepare_build(self, build_keys, build_page):
        """Sort the build side ONCE per join (LookupSourceFactory analog) —
        probe-page kernels consume the prepared tuple without re-sorting."""
        prep = cached_kernel(("join-prep", tuple(build_keys)),
                             lambda: prepare_build(build_keys))
        return prep(build_page)

    # direct-address tables: pow2 sizes bound compile-shape diversity; the
    # slot cap bounds HBM (64M slots = 256MB int32 for in-memory builds)
    _DENSE_MAX_SLOTS = 1 << 26

    def _prepare_with_dense(self, build_keys, build_page):
        """prepare_build + the dense-key decision: fetch (max_run, kmin,
        kmax) in ONE round trip; when the live-key span is small (dense
        surrogate keys — every TPC-H/DS join), append a direct-address
        lookup table so probe kernels cost one gather instead of a
        sort-engine searchsorted pass per buffer.

        Returns (prepared [+ table], max_run, dense)."""
        prepared = self._prepare_build(build_keys, build_page)
        max_run, kmin, kmax = (int(x) for x in jax.device_get(
            [prepared[7], prepared[8], prepared[9]]))
        span = kmax - kmin + 1 if kmax >= kmin else 0
        with_table = self._dense_table_for(prepared, build_page, span)
        if with_table is not None:
            return with_table, max_run, True
        return prepared, max_run, False

    def _dense_table_for(self, prepared, build_page, span: int):
        """The ONE dense-gather decision + table build (shared by the
        spill paths' _prepare_with_dense and the router's
        _prepare_probe — the limit formula and kernel key must never
        diverge between them): prepared + direct-address table when the
        live-key span qualifies, else None."""
        from trino_tpu.ops.join import build_dense_table
        limit = min(max(4 * build_page.capacity, 1 << 20),
                    self._DENSE_MAX_SLOTS)
        if not 0 < span <= limit:
            return None
        size = _next_pow2(span)
        table_op = cached_kernel(
            ("dense-table", size),
            lambda: build_dense_table(size))
        return prepared + (table_op(prepared[1], prepared[3],
                                    prepared[8]),)

    def _prepare_probe(self, build_keys, build_page, mxu_ok: bool = True):
        """prepare_build + the per-join PROBE-STRATEGY router (the MXU
        path's decision point — ROADMAP item 1): fetch (max_run, kmin,
        kmax, distinct live keys) in ONE round trip, then pick

          'mxu'    — mxu_join_enabled, the live-key span fits
                     mxu_join_max_slots, the OBSERVED density (distinct
                     live build keys / span) clears
                     mxu_join_density_threshold, and the build stays
                     under the f32-exactness bound: probes run as
                     blocked indicator matmuls on the matrix unit
                     against a per-key [count, pos] table
                     (ops/join_mxu.py);
          'dense'  — small span, mxu declined: direct-address gather;
          'search' — everything else: sort-engine searchsorted.

        The CBO stamp (JoinNode.join_strategy, EXPLAIN's `join
        strategy:` line) is the plan-time candidate; this router holds
        the runtime truth — `mxu_joins` counts what actually ran.
        Returns (prepared [+ table], max_run, mode)."""
        from trino_tpu.ops import join_mxu
        prepared = self._prepare_build(build_keys, build_page)
        mxu_on = mxu_ok and bool(self.session.get("mxu_join_enabled"))
        fetch = [prepared[7], prepared[8], prepared[9]]
        if mxu_on:
            nd_op = cached_kernel(("mxu-ndistinct",),
                                  lambda: join_mxu.distinct_live_keys)
            fetch.append(nd_op(prepared[1], prepared[3]))
        got = [int(x) for x in jax.device_get(fetch)]
        max_run, kmin, kmax = got[:3]
        ndistinct = got[3] if mxu_on else 0
        span = kmax - kmin + 1 if kmax >= kmin else 0
        if mxu_on \
                and 0 < span <= int(self.session.get(
                    "mxu_join_max_slots")) \
                and build_page.capacity < join_mxu.MAX_EXACT_ROWS \
                and ndistinct >= span * float(self.session.get(
                    "mxu_join_density_threshold")):
            size = 1 << max((span - 1).bit_length(), 7)
            table_op = cached_kernel(
                ("mxu-table", size),
                lambda: join_mxu.build_count_pos_table(size))
            table = table_op(prepared[1], prepared[3], prepared[8])
            return prepared + (table,), max_run, "mxu"
        with_table = self._dense_table_for(prepared, build_page, span)
        if with_table is not None:
            return with_table, max_run, "dense"
        return prepared, max_run, "search"

    def _mxu_stream(self, stream, slots: int, ncols: int = 2):
        """Wrap a probe stream in matrix-unit accounting: one mxu_joins
        count per routed join, and each probe dispatch's cost-model MACs
        on mxu_flops — the counters the bench and PR 12's attribution
        read."""
        from trino_tpu.ops.join_mxu import lookup_flops
        col = self.collector
        if col is not None:
            col.mxu_join()
        self._adaptive_span("join-mxu-route", slots=slots)
        it = stream.iter_pages() if hasattr(stream, "iter_pages") \
            else iter(stream)

        def gen():
            for page in it:
                if col is not None:
                    col.add_mxu_flops(
                        lookup_flops(page.capacity, slots, ncols))
                yield page
        return gen()

    def _exec_right_join(self, node: JoinNode) -> PageStream:
        flipped = JoinNode(
            JoinKind.LEFT, node.right, node.left,
            tuple(JoinClause(c.right, c.left) for c in node.criteria),
            node.filter, node.distribution)
        stream = self.execute(flipped)
        return _reorder_stream(stream,
                               node.left.outputs + node.right.outputs)

    def _exec_full_join(self, node: JoinNode) -> PageStream:
        """FULL outer: LEFT-join streaming over probe pages while
        accumulating which build rows matched, then emit the never-matched
        build rows null-extended (LookupOuterOperator analog)."""
        from trino_tpu.ops.join import unmatched_build_page
        if node.filter is not None:
            raise ExecutionError(
                "non-inner join with residual filter not supported")
        probe_stream = self.execute(node.left)
        build_stream = self.execute(node.right)
        probe_lay, _ = _layout(probe_stream.symbols)
        build_lay, _ = _layout(build_stream.symbols)
        probe_keys = [probe_lay[c.left.name] for c in node.criteria]
        build_keys = [build_lay[c.right.name] for c in node.criteria]
        build_page = self._collect(build_stream)
        out_symbols = node.left.outputs + node.right.outputs
        probe_meta = tuple((s.type, None) for s in node.left.outputs)

        def full_op(cap: int):
            return cached_kernel(
                ("fulljoin", tuple(probe_keys), tuple(build_keys), cap),
                lambda: hash_join(probe_keys, build_keys, JoinType.FULL,
                                  output_capacity=cap, prepared=True))

        def gen():
            import itertools
            nonlocal probe_meta
            bp = build_page
            if bp is None:
                bp = self._null_build_page(node.right.outputs)
            prepared = self._prepare_build(build_keys, bp)
            matched = jnp.zeros(bp.capacity, dtype=jnp.bool_)
            it = self._coalesce_stream(probe_stream).iter_pages()
            while True:
                # lookahead-batched overflow resolution (same transfer
                # discipline as _run_with_overflow: one device_get per
                # window, not per page)
                batch = list(itertools.islice(it, 8))
                if not batch:
                    break
                results = []
                for page in batch:
                    probe_meta = tuple(
                        (c.type, c.dictionary) for c in page.columns)
                    cap = max(self.page_capacity, page.capacity)
                    results.append((cap, full_op(cap)(page, prepared)))
                totals = jax.device_get([t for _, (_, t, _) in results])
                for page, (cap, (out, _, bm)), total in zip(
                        batch, results, totals):
                    total = int(total)
                    while total > cap:
                        cap = _next_pow2(total)
                        out, t, bm = full_op(cap)(page, prepared)
                        total = int(t)
                    matched = matched | bm
                    yield out
            if int(bp.num_rows) == 0:
                return
            # once-per-query finisher: executed eagerly (its dictionaries
            # are per-query objects — caching on them would pin string
            # pools in the process-lifetime kernel cache forever)
            yield unmatched_build_page(probe_meta)(bp, matched)
        return PageStream(gen(), out_symbols)

    def _null_build_page(self, symbols: Tuple[Symbol, ...]) -> Page:
        cols = []
        for s in symbols:
            cols.append(Column(jnp.zeros(8, dtype=s.type.dtype),
                               jnp.zeros(8, dtype=jnp.bool_), s.type, None))
        return Page(tuple(cols), 0)

    def _exec_cross_join(self, node: JoinNode) -> PageStream:
        probe_stream = self.execute(node.left)
        build_stream = self.execute(node.right)
        build_page = self._collect(build_stream)
        out_symbols = node.left.outputs + node.right.outputs

        def gen():
            if build_page is None:
                return
            nb = int(build_page.num_rows)
            if nb == 1:
                # scalar-subquery path: broadcast the single build row
                def build():
                    def attach(p, b):
                        bcols = tuple(
                            Column(jnp.broadcast_to(c.values[:1],
                                                    (p.capacity,)),
                                   None if c.valid is None else
                                   jnp.broadcast_to(c.valid[:1],
                                                    (p.capacity,)),
                                   c.type, c.dictionary)
                            for c in b.columns)
                        return Page(tuple(p.columns) + bcols, p.num_rows)
                    return attach
                run = cached_kernel(("cross-attach",), build)
                for page in probe_stream.iter_pages():
                    yield run(page, build_page)
                return
            # general cross join: bounded expansion
            for page in probe_stream.iter_pages():
                np_rows = int(page.num_rows)
                if np_rows == 0:
                    continue
                total = np_rows * nb
                if total > 4 * 1024 * 1024:
                    raise ExecutionError(
                        f"cross join too large ({total} rows)")
                cap = _next_pow2(total)
                idx = jnp.arange(cap, dtype=jnp.int32)
                pi = jnp.minimum(idx // nb, page.capacity - 1)
                bi = jnp.minimum(idx % nb, build_page.capacity - 1)
                pcols = tuple(c.gather(pi) for c in page.columns)
                bcols = tuple(c.gather(bi) for c in build_page.columns)
                yield Page(pcols + bcols, total)
        return PageStream(gen(), out_symbols)

    @staticmethod
    def _semijoin_filter_mode(node: FilterNode):
        """('semi'|'anti', rest_conjuncts) when the filter consumes the
        match flag as a plain top-level conjunct; None -> generic path."""
        semi: SemiJoinNode = node.source
        match_name = semi.match_symbol.name
        mode: Optional[str] = None
        rest: List[RowExpression] = []
        from trino_tpu.planner.optimizer import conjuncts
        for c in conjuncts(node.predicate):
            if isinstance(c, SymbolRef) and c.name == match_name:
                mode = "semi"
            elif isinstance(c, SpecialForm) and c.kind is SpecialKind.NOT \
                    and isinstance(c.args[0], SymbolRef) \
                    and c.args[0].name == match_name:
                mode = "anti"
            elif match_name in _symbol_names(c):
                return None
            else:
                rest.append(c)
        if mode is None:
            return None
        return mode, rest

    def _exec_semijoin_filter(self, node: FilterNode) -> PageStream:
        semi: SemiJoinNode = node.source
        from trino_tpu.planner.optimizer import combine
        mode, rest = self._semijoin_filter_mode(node)

        probe_stream = self.execute(semi.source)
        build_stream = self.execute(semi.filtering_source)
        probe_lay, probe_typ = _layout(probe_stream.symbols)
        build_lay, _ = _layout(build_stream.symbols)
        probe_keys = [probe_lay[s.name] for s in semi.source_keys]
        build_keys = [build_lay[s.name] for s in semi.filtering_keys]
        build_page = self._collect(build_stream)
        jt = JoinType.SEMI if mode == "semi" else JoinType.ANTI
        rest_pred = combine(rest)
        rest_lowered, rest_params = self._hoist(
            None if rest_pred is None else
            lower_expr(rest_pred, probe_lay, probe_typ))

        def semi_op(cap: int, mode: str = "search"):
            def build():
                op = hash_join(probe_keys, build_keys, jt,
                               output_capacity=cap, prepared=True,
                               lookup=mode, null_aware=semi.null_aware)
                fn = None if rest_lowered is None \
                    else compile_filter(rest_lowered)

                def run(p, b, g):
                    out, total = op(p, b)
                    if fn is not None:
                        out = out.filter(fn(out, g))
                    # surviving rows all share one match value (semi: True,
                    # anti: False); emit it so pages carry EXACTLY the
                    # node's declared outputs — downstream operators lower
                    # expressions against declared layouts
                    mcol = Column(
                        jnp.broadcast_to(jnp.asarray(mode == "semi"),
                                         (out.capacity,)),
                        None, T.BOOLEAN, None)
                    return Page(out.columns + (mcol,), out.num_rows), total
                return run
            kernel = cached_kernel(
                ("semijoin", tuple(probe_keys), tuple(build_keys), jt,
                 cap, rest_lowered, semi.null_aware, mode), build,
                params=rest_params)
            return lambda p, b: kernel(p, b, rest_params)

        def gen():
            bp = build_page
            if bp is None:
                if jt == JoinType.SEMI:
                    return
                bp = self._null_build_page(semi.filtering_source.outputs)
            try:
                prepared, _max_run, mode = self._prepare_probe(
                    build_keys, bp, mxu_ok=len(build_keys) == 1)
                probe_in = self._coalesce_stream(probe_stream)
                if mode == "mxu":
                    probe_in = self._mxu_stream(probe_in,
                                                prepared[10].shape[0])
                yield from _run_with_overflow(
                    probe_in, prepared,
                    lambda cap: semi_op(cap, mode), self.page_capacity)
            finally:
                self._free_collected(build_page)
        return PageStream(gen(),
                          semi.source.outputs + (semi.match_symbol,))

    def _exec_SemiJoinNode(self, node: SemiJoinNode) -> PageStream:
        """Bare semi join: emit probe rows + boolean match channel
        (HashSemiJoinOperator). Used when the match symbol escapes a direct
        Filter (e.g. stacked EXISTS predicates)."""
        probe_stream = self.execute(node.source)
        build_stream = self.execute(node.filtering_source)
        probe_lay, _ = _layout(probe_stream.symbols)
        build_lay, _ = _layout(build_stream.symbols)
        probe_keys = [probe_lay[s.name] for s in node.source_keys]
        build_keys = [build_lay[s.name] for s in node.filtering_keys]
        build_page = self._collect(build_stream)
        out_symbols = node.source.outputs + (node.match_symbol,)

        def mark_op(cap: int, mode: str = "search"):
            return cached_kernel(
                ("markjoin", tuple(probe_keys), tuple(build_keys), cap,
                 node.null_aware, mode),
                lambda: hash_join(probe_keys, build_keys, JoinType.MARK,
                                  output_capacity=cap, prepared=True,
                                  lookup=mode,
                                  null_aware=node.null_aware))

        def no_match(page: Page) -> Page:
            mark = Column(jnp.zeros(page.capacity, dtype=jnp.bool_), None,
                          T.BOOLEAN, None)
            return Page(tuple(page.columns) + (mark,), page.num_rows)

        def gen():
            bp = build_page
            if bp is None:
                for page in probe_stream.iter_pages():
                    yield no_match(page)
                return
            try:
                prepared, _max_run, mode = self._prepare_probe(
                    build_keys, bp, mxu_ok=len(build_keys) == 1)
                probe_in = self._coalesce_stream(probe_stream)
                if mode == "mxu":
                    probe_in = self._mxu_stream(probe_in,
                                                prepared[10].shape[0])
                yield from _run_with_overflow(
                    probe_in, prepared,
                    lambda cap: mark_op(cap, mode), self.page_capacity)
            finally:
                self._free_collected(build_page)
        return PageStream(gen(), out_symbols)

    def _exec_UnnestNode(self, node) -> PageStream:
        """UNNEST expansion (operator/unnest/UnnestOperator.java, static-
        shape cut): per page, element counts -> cumsum offsets -> one
        searchsorted maps output slots to source rows; elements gather
        from the [capacity, L] plane, replicated columns gather at the
        source row. Output capacity sizes from a per-page count fetch."""
        src = self.execute(node.source)
        lay, _ = _layout(src.symbols)
        arr_ch = lay[node.arrays[0].name]
        is_map = len(node.elements[0]) == 2
        with_ord = node.ordinality is not None

        def count_op_build():
            def run(page: Page):
                c = page.column(arr_ch)
                live = page.row_mask() & c.valid_mask()
                lens = jnp.where(live, c.lengths, 0)
                return jnp.sum(lens).astype(jnp.int64)
            return run
        count_op = cached_kernel(("unnest-count", arr_ch), count_op_build)

        def expand_op(cap: int):
            def build():
                def run(page: Page):
                    c = page.column(arr_ch)
                    n = page.capacity
                    L = c.values.shape[1]
                    live = page.row_mask() & c.valid_mask()
                    lens = jnp.where(live, c.lengths, 0).astype(jnp.int64)
                    offsets = jnp.cumsum(lens)
                    starts = offsets - lens
                    total = offsets[-1]
                    out_idx = jnp.arange(cap, dtype=jnp.int64)
                    prow = jnp.searchsorted(
                        offsets, out_idx, side="right").astype(jnp.int32)
                    prow_c = jnp.minimum(prow, n - 1)
                    within = (out_idx - jnp.take(starts, prow_c,
                                                 mode="clip")
                              ).astype(jnp.int32)
                    within_c = jnp.clip(within, 0, max(L - 1, 0))
                    cols = [col.gather(prow_c) for col in page.columns]
                    plane = jnp.take(c.values, prow_c, axis=0,
                                     mode="clip")
                    elem = jnp.take_along_axis(
                        plane, within_c[:, None], axis=1)[:, 0]
                    el_types = node.elements[0]
                    cols.append(Column(elem, None, el_types[0].type,
                                       c.dictionary))
                    if is_map:
                        aplane = jnp.take(c.aux, prow_c, axis=0,
                                          mode="clip")
                        aval = jnp.take_along_axis(
                            aplane, within_c[:, None], axis=1)[:, 0]
                        cols.append(Column(aval, None, el_types[1].type,
                                           c.aux_dictionary))
                    if with_ord:
                        cols.append(Column(within.astype(jnp.int64) + 1,
                                           None, T.BIGINT, None))
                    rows = jnp.minimum(total, cap).astype(jnp.int32)
                    return Page(tuple(cols), rows)
                return run
            return cached_kernel(
                ("unnest", arr_ch, cap, is_map, with_ord), build)

        def gen():
            for page in src.iter_pages():
                total = int(jax.device_get(count_op(page)))
                if total == 0:
                    continue
                yield expand_op(_next_pow2(total))(page)
        return PageStream(gen(), node.outputs)

    def _exec_AssignUniqueIdNode(self, node) -> PageStream:
        """AssignUniqueIdOperator: tag rows with a stable unique id.

        Ids are page_capacity_offset + row_position (NOT dense: padding rows
        consume ids too), so they are unique and — because scan order is
        deterministic — re-executing the same subtree (shared by a
        decorrelated EXISTS) reproduces identical ids."""
        src = self.execute(node.source)

        def build():
            def tag(page, offset):
                idx = (jnp.arange(page.capacity, dtype=jnp.int64)
                       + offset)
                col = Column(idx, None, T.BIGINT, None)
                return Page(tuple(page.columns) + (col,), page.num_rows)
            return tag
        tag = cached_kernel(("assign-unique-id",), build)

        def gen():
            # advance by page CAPACITY, not num_rows: padding rows get ids
            # too, so live rows of later pages can never collide with them
            # (uniqueness is this symbol's whole contract), and no per-page
            # num_rows host sync is needed
            offset = 0
            for page in src.iter_pages():
                yield tag(page, jnp.int64(offset))
                offset += page.capacity
        return PageStream(gen(), node.source.outputs + (node.id_symbol,))

    def _exec_EnforceSingleRowNode(self, node) -> PageStream:
        src = self.execute(node.source)

        def gen():
            page = self._collect(src)
            if page is None:
                # zero rows -> one all-null row (EnforceSingleRowOperator)
                yield Page(self._null_build_page(node.outputs).columns, 1)
                return
            n = int(page.num_rows)
            if n > 1:
                raise ExecutionError(
                    "Scalar sub-query has returned multiple rows")
            yield page
        return PageStream(gen(), node.outputs)

    def _exec_UnionNode(self, node: UnionNode) -> PageStream:
        nsyms = len(node.symbols)

        def gen():
            # start every child and peek one page each: string columns from
            # different tables carry different dictionaries, and blocking
            # consumers (sort/agg/join build) concat across children — so
            # re-encode onto a shared union dictionary. Pages of one child
            # stream share a per-column dictionary, so one peek suffices.
            children = []
            for j, child in enumerate(node.children):
                stream = self.execute(child)
                lay, _ = _layout(stream.symbols)
                order = [lay[node.mappings[i][j].name] for i in range(nsyms)]
                it = iter(stream.iter_pages())
                first = next(it, None)
                children.append([it, first, order])
            remaps = _union_dictionary_remaps(node.symbols, children)
            for it, first, order in children:
                for page in _chain_first(first, it):
                    if int(page.num_rows) == 0:
                        continue
                    cols = []
                    for i, ch in enumerate(order):
                        col = page.column(ch)
                        remap = remaps[i].get(id(col.dictionary)) \
                            if remaps[i] else None
                        if remap is not None:
                            table, union_dict = remap
                            codes = jnp.take(table,
                                             jnp.clip(col.values, 0),
                                             mode="clip")
                            col = Column(codes, col.valid, col.type,
                                         union_dict)
                        cols.append(col)
                    yield Page(tuple(cols), page.num_rows)
        return PageStream(gen(), node.symbols)

    def _exec_ExchangeNode(self, node: ExchangeNode) -> PageStream:
        # single-device execution: exchanges are pass-through (the
        # distributed executor lowers them to collectives)
        return self.execute(node.source)

    def _exec_WindowNode(self, node: WindowNode) -> PageStream:
        """WindowOperator: blocking sort-partitioned evaluation
        (operator/window/WindowOperator.java; ops/window.py kernel)."""
        from trino_tpu.ops.window import WindowSpec, window
        src = self.execute(node.source)
        lay, typ = _layout(src.symbols)
        part = tuple(lay[s.name] for s in node.partition_by)
        okeys = tuple(SortKey(lay[o.symbol.name], o.ascending, o.nulls_first)
                      for o in node.order_by)
        specs = []
        for out_sym, wf in node.functions:
            whole, bounds = self._lower_frame(node, wf)
            args = []
            for a in wf.args:
                if not isinstance(a, SymbolRef):
                    raise ExecutionError("window args must be pre-projected")
                args.append(lay[a.name])
            specs.append(WindowSpec(wf.name.lower(), tuple(args),
                                    out_sym.type, whole,
                                    wf.frame_type == "ROWS", bounds))
        win = cached_kernel(
            ("window", part, okeys, tuple(specs)),
            lambda: window(part, okeys, specs))

        def gen():
            page = self._collect(src)
            if page is None:
                return
            try:
                yield win(page)
            finally:
                self._free_collected(page)
        return PageStream(gen(), node.outputs)

    @staticmethod
    def _lower_frame(node: WindowNode, wf):
        """WindowFunction frame -> (frame_whole, bounds) for WindowSpec.

        Ranking functions ignore frames (SQL). The default/unbounded frames
        map onto the legacy whole/running paths; literal ROWS offsets become
        static (start_off, end_off) bounds; value-based RANGE offsets and
        GROUPS frames fail loud. Reference: FramedWindowFunction.java +
        sql/planner/plan/WindowNode.Frame."""
        from trino_tpu.ops.window import RANKING

        def literal_offset(value, kind: str) -> int:
            if not isinstance(value, Literal) or \
                    not isinstance(value.value, int):
                raise ExecutionError(
                    "window frame offsets must be integer literals")
            v = int(value.value)
            if v < 0:
                raise ExecutionError("window frame offset must be >= 0")
            return -v if kind == "PRECEDING" else v

        if wf.name.lower() in RANKING:
            return (not node.order_by), None
        st, sv = wf.start_type, wf.start_value
        et, ev = wf.end_type, wf.end_value
        if st == "UNBOUNDED_PRECEDING" and et == "UNBOUNDED_FOLLOWING":
            return True, None
        if not node.order_by:
            return True, None
        if st == "UNBOUNDED_PRECEDING" and et == "CURRENT_ROW":
            return False, None                     # running frame
        if wf.frame_type == "GROUPS":
            raise ExecutionError("GROUPS window frames not supported")
        if wf.frame_type == "RANGE":
            raise ExecutionError(
                "RANGE frames with value offsets not supported")
        start_off = None if st == "UNBOUNDED_PRECEDING" else (
            0 if st == "CURRENT_ROW" else literal_offset(sv, st))
        end_off = None if et == "UNBOUNDED_FOLLOWING" else (
            0 if et == "CURRENT_ROW" else literal_offset(ev, et))
        return False, (start_off, end_off)

    def _exec_OutputNode(self, node: OutputNode) -> PageStream:
        src = self.execute(node.source)
        return _reorder_stream(src, node.symbols)

    def _exec_TableWriterNode(self, node: TableWriterNode) -> PageStream:
        src = self.execute(node.source)
        lay, _ = _layout(src.symbols)
        order = [lay[s.name] for s in node.column_symbols]
        conn = self.metadata.connector(node.catalog)
        sink = conn.page_sink(node.table, write_token=self.write_token)
        if hasattr(sink, "set_commit_options"):
            # session manifest-log retention depth rides to the commit;
            # the MV refresher arms a replace-commit channel on the
            # session (internal, never SQL-settable): when THIS write's
            # target matches, the sink swaps the table's whole file set
            # and stamps the refresh watermark in the same commit
            opts = {"history": int(self.session.get(
                "lake_manifest_history"))}
            mv_commit = getattr(self.session, "_mv_commit", None)
            if mv_commit is not None and mv_commit.get("table") == (
                    node.catalog, node.table.name.schema,
                    node.table.name.table):
                opts["replace"] = bool(mv_commit.get("replace", True))
                opts["mv_meta"] = mv_commit.get("mv_meta")
            sink.set_commit_options(**opts)

        def gen():
            # idempotent-write protocol (connector/spi.py): pages STAGE
            # under the write token; finish() commits once per token.
            # Any failure — an injected fault, a slice-boundary cancel,
            # a killed victim, even generator abandonment — aborts the
            # staging, so a retried attempt starts from zero staged rows
            # and a committed token never commits twice.
            written = 0
            try:
                for page in src.iter_pages():
                    self._checkpoint()
                    n = int(page.num_rows)
                    if n == 0:
                        continue
                    out = Page(tuple(page.column(c) for c in order), n)
                    sink.append_page(out)
                    written += n
                sink.finish()
            except BaseException:   # GeneratorExit included: an
                sink.abort()        # abandoned writer must not leak
                raise               # staged rows into a later commit
            col = Column(jnp.asarray(np.array([written] * 8,
                                              dtype=np.int64)),
                         None, T.BIGINT, None)
            yield Page((col,), 1)
        return PageStream(gen(), node.outputs)


def _reorder_stream(src: PageStream, symbols: Tuple[Symbol, ...]
                    ) -> PageStream:
    """Select/reorder a stream's columns to `symbols` (identity is free)."""
    lay, _ = _layout(src.symbols)
    order = tuple(lay[s.name] for s in symbols)
    if order == tuple(range(len(src.symbols))):
        return PageStream(src.pages, symbols, src.pending)
    return PageStream(
        src.pages, symbols,
        src.pending + ((("select", order),
                        lambda: lambda p, g: Page(
                            tuple(p.columns[c] for c in order),
                            p.num_rows), ()),))




def _byte_bounded_batches(it: Iterator[Page], budget_bytes: int,
                          max_pages: int = 8) -> Iterator[List[Page]]:
    """Lookahead batching bounded by BYTES, not page count: dispatching 8
    32M-row probe buffers ahead of one sync pinned >10GB of intermediates
    in HBM at SF100 (the round-4 OOM). Small pages still amortize the sync
    across up to max_pages dispatches."""
    batch: List[Page] = []
    used = 0
    for page in it:
        nbytes = sum(c.nbytes for c in page.columns)
        if batch and (used + nbytes > budget_bytes
                      or len(batch) >= max_pages):
            yield batch
            batch, used = [], 0
        batch.append(page)
        used += nbytes
    if batch:
        yield batch


def _run_with_overflow(probe_stream: PageStream, build_page: Page,
                       make_op, page_capacity: int) -> Iterator[Page]:
    """Dispatch a capacity-laddered binary page op over probe pages in
    bounded lookahead windows, resolving each window's overflow counters in
    one batched device_get (a sync per page costs a full round trip on
    remote TPUs, but dispatching the whole stream before the first sync
    would pin every intermediate output in HBM simultaneously); only pages
    that actually overflowed re-run at the next capacity bucket (SURVEY §7
    contract). Accepts a PageStream or a bare page iterator (the
    partitioned join streams restaged probe chunks directly)."""
    it = probe_stream.iter_pages() \
        if hasattr(probe_stream, "iter_pages") else iter(probe_stream)
    for probe_pages in _byte_bounded_batches(it, 1 << 29):
        results = []
        for page in probe_pages:
            cap = max(page_capacity, page.capacity)
            results.append((cap, make_op(cap)(page, build_page)))
        totals = jax.device_get([t for _, (_, t) in results])
        for page, (cap, (out, _)), total in zip(probe_pages, results,
                                                totals):
            total = int(total)
            while total > cap:
                cap = _next_pow2(total)
                out, t = make_op(cap)(page, build_page)
                total = int(t)
            # join outputs inherit probe capacity; shrink heavily padded
            # ones so downstream sorts run at live size
            tight = _next_pow2(max(total, 1))
            if cap > 2 * tight:
                out = out.shrink_to(tight)
            yield out


def _chain_first(first: Optional[Page], rest: Iterator[Page]) -> Iterator[Page]:
    if first is not None:
        yield first
    yield from rest


def _drain_then(pages: List[Page], rest: Iterator[Page]) -> Iterator[Page]:
    """Yield the buffered pages DROPPING each reference as it is
    consumed (itertools.chain would pin the whole list — and its HBM —
    until exhaustion; this path exists precisely because memory is
    tight), then continue with the live stream."""
    while pages:
        yield pages.pop(0)
    yield from rest


def _union_dictionary_remaps(symbols, children):
    """Per output column: None when all children already share a dictionary,
    else {id(child_dict): (code_remap_device_array, union_dictionary)}."""
    from trino_tpu.page import union_dictionaries
    remaps: List[Optional[Dict[int, tuple]]] = []
    for i, sym in enumerate(symbols):
        dicts = []
        for it, first, order in children:
            if first is None:
                continue
            d = first.column(order[i]).dictionary
            if d is not None:
                dicts.append(d)
        uniq = {id(d): d for d in dicts}
        if len(uniq) <= 1:
            remaps.append(None)
            continue
        union, tables = union_dictionaries(list(uniq.values()))
        remaps.append({did: (tbl, union)
                       for did, tbl in zip(uniq, tables)})
    return remaps


def _valid_arr(valid: List[bool], cap: int) -> Optional[jnp.ndarray]:
    if all(valid):
        return None
    arr = np.zeros(cap, dtype=bool)
    arr[:len(valid)] = valid
    return jnp.asarray(arr)


def _symbol_names(e: RowExpression) -> set:
    out = set()

    def visit(x):
        if isinstance(x, SymbolRef):
            out.add(x.name)
        for c in x.children():
            visit(c)
    visit(e)
    return out
