"""Local execution: lower a logical plan to streaming page pipelines.

Reference parity: sql/planner/LocalExecutionPlanner.java:420 — each plan node
maps to an operator implementation over Pages (visitTableScan:1733,
visitFilter/visitProject via ScanFilterAndProject:1606, visitAggregation:1534,
visitJoin:2109, visitTopN, visitSort, visitLimit, visitSemiJoin, ...).

Execution model (Driver.java replacement): a node executes to an iterator of
fixed-capacity Pages plus a symbol layout. Device work per page runs under
jit — traces cache on (capacity, dtypes), so steady-state streaming is one
compiled XLA call per page per pipeline stage. Blocking operators (agg, sort,
join build) consume their input eagerly, as their Java counterparts do across
addInput/finish.

Dynamic row counts under static shapes (SURVEY §7 hard part 1): operators
carry a true-total scalar; when an output overflows its static capacity the
executor doubles the capacity bucket and re-runs (hash_join contract).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.connector.spi import Split
from trino_tpu.expr.compiler import compile_expression, compile_filter
from trino_tpu.expr.ir import (Call, InputRef, Literal, RowExpression,
                               SpecialForm, SpecialKind, SymbolRef)
from trino_tpu.metadata import Metadata, Session
from trino_tpu.ops import (AggSpec, JoinType, SortKey, Step, hash_aggregate,
                           hash_join, order_by, top_n)
from trino_tpu.page import Column, Page, concat_pages
from trino_tpu.planner.nodes import (
    AggregationNode, AggStep, DistinctLimitNode, EnforceSingleRowNode,
    ExchangeNode, FilterNode, GroupIdNode, JoinClause, JoinKind, JoinNode,
    LimitNode, OffsetNode, OutputNode, PlanNode, ProjectNode, SemiJoinNode,
    SortNode, Symbol, TableScanNode, TopNNode, UnionNode, ValuesNode,
    WindowNode, TableWriterNode)


class ExecutionError(Exception):
    pass


def lower_expr(e: RowExpression, layout: Dict[str, int],
               types: Dict[str, T.Type]) -> RowExpression:
    """SymbolRef -> InputRef against a page layout (the compiled-PageProcessor
    channel mapping step)."""
    if isinstance(e, SymbolRef):
        if e.name not in layout:
            raise ExecutionError(f"symbol {e.name} not in layout")
        return InputRef(layout[e.name], types[e.name])
    if isinstance(e, Call):
        return Call(e.name, tuple(lower_expr(a, layout, types)
                                  for a in e.args), e.type)
    if isinstance(e, SpecialForm):
        return SpecialForm(e.kind, tuple(lower_expr(a, layout, types)
                                         for a in e.args), e.type)
    return e


def _layout(symbols: Sequence[Symbol]) -> Tuple[Dict[str, int],
                                                Dict[str, T.Type]]:
    lay = {s.name: i for i, s in enumerate(symbols)}
    typ = {s.name: s.type for s in symbols}
    return lay, typ


def _next_pow2(n: int) -> int:
    out = 1024
    while out < n:
        out *= 2
    return out


@dataclasses.dataclass
class PageStream:
    pages: Iterator[Page]
    symbols: Tuple[Symbol, ...]


class LocalExecutionPlanner:
    """Single-process executor over one device (LocalQueryRunner's engine)."""

    def __init__(self, metadata: Metadata, session: Session):
        self.metadata = metadata
        self.session = session
        self.page_capacity = int(session.get("page_capacity"))

    # ------------------------------------------------------------ dispatch

    def execute(self, node: PlanNode) -> PageStream:
        name = type(node).__name__
        method = getattr(self, f"_exec_{name}", None)
        if method is None:
            raise ExecutionError(f"no executor for {name}")
        return method(node)

    # ---------------------------------------------------------------- leaf

    def _exec_TableScanNode(self, node: TableScanNode) -> PageStream:
        conn = self.metadata.connector(node.catalog)
        columns = [c for _, c in node.assignments]
        splits = conn.split_manager.get_splits(node.table, target_splits=1)

        def gen():
            for split in splits:
                yield from conn.page_source.pages(split, columns,
                                                  self.page_capacity)
        return PageStream(gen(), tuple(s for s, _ in node.assignments))

    def _exec_ValuesNode(self, node: ValuesNode) -> PageStream:
        cols = []
        n = len(node.rows)
        cap = max(_next_pow2(n), 8)
        for i, sym in enumerate(node.symbols):
            typ = sym.type
            vals = []
            valid = []
            for row in node.rows:
                lit = row[i]
                if not isinstance(lit, Literal):
                    raise ExecutionError("VALUES row is not literal")
                vals.append(0 if lit.value is None else lit.value)
                valid.append(lit.value is not None)
            if T.is_string(typ):
                from trino_tpu.page import Dictionary
                d, codes = Dictionary.build(np.asarray(
                    [v if isinstance(v, str) else "" for v in vals],
                    dtype=object))
                arr = np.zeros(cap, dtype=np.int32)
                arr[:n] = codes
                col = Column(jnp.asarray(arr), _valid_arr(valid, cap), typ, d)
            else:
                arr = np.zeros(cap, dtype=T.to_numpy_dtype(typ))
                arr[:n] = vals
                col = Column(jnp.asarray(arr), _valid_arr(valid, cap), typ,
                             None)
            cols.append(col)
        page = Page(tuple(cols), n)
        return PageStream(iter([page]), node.symbols)

    # ----------------------------------------------------------- streaming

    def _exec_FilterNode(self, node: FilterNode) -> PageStream:
        # Filter(SemiJoin) fuses into semi/anti probe (LocalExecutionPlanner
        # visitFilter's special-cased semi-join consumption)
        if isinstance(node.source, SemiJoinNode):
            return self._exec_semijoin_filter(node)
        src = self.execute(node.source)
        lay, typ = _layout(src.symbols)
        pred = lower_expr(node.predicate, lay, typ)
        fn = jax.jit(lambda p, f=compile_filter(pred): p.filter(f(p)))

        def gen():
            for page in src.pages:
                yield fn(page)
        return PageStream(gen(), src.symbols)

    def _exec_ProjectNode(self, node: ProjectNode) -> PageStream:
        src = self.execute(node.source)
        lay, typ = _layout(src.symbols)
        exprs = [lower_expr(e, lay, typ) for _, e in node.assignments]
        fns = [compile_expression(e) for e in exprs]

        @jax.jit
        def run(page):
            return Page(tuple(fn(page) for fn in fns), page.num_rows)

        def gen():
            for page in src.pages:
                yield run(page)
        return PageStream(gen(), tuple(s for s, _ in node.assignments))

    def _exec_LimitNode(self, node: LimitNode) -> PageStream:
        src = self.execute(node.source)

        def gen():
            remaining = node.count
            for page in src.pages:
                n = int(page.num_rows)
                if n >= remaining:
                    yield Page(page.columns, remaining)
                    return
                remaining -= n
                yield page
        return PageStream(gen(), src.symbols)

    def _exec_OffsetNode(self, node: OffsetNode) -> PageStream:
        src = self.execute(node.source)

        def gen():
            to_skip = node.count
            for page in src.pages:
                n = int(page.num_rows)
                if to_skip >= n:
                    to_skip -= n
                    continue
                if to_skip > 0:
                    idx = jnp.arange(page.capacity, dtype=jnp.int32) + to_skip
                    gathered = tuple(c.gather(idx) for c in page.columns)
                    page = Page(gathered, n - to_skip)
                    to_skip = 0
                yield page
        return PageStream(gen(), src.symbols)

    # ------------------------------------------------------------ blocking

    def _collect(self, stream: PageStream) -> Optional[Page]:
        pages = [p for p in stream.pages if int(p.num_rows) > 0]
        if not pages:
            return None
        if len(pages) == 1:
            return pages[0]
        return concat_pages(pages)

    def _exec_AggregationNode(self, node: AggregationNode) -> PageStream:
        src = self.execute(node.source)
        lay, typ = _layout(src.symbols)
        key_channels = [lay[s.name] for s in node.group_by]
        specs = []
        for out_sym, call in node.aggregations:
            if call.args:
                arg = call.args[0]
                assert isinstance(arg, SymbolRef)
                input_ch: Optional[int] = lay[arg.name]
                in_type: Optional[T.Type] = typ[arg.name]
            else:
                input_ch, in_type = None, None
            mask_ch = None
            if call.filter is not None:
                assert isinstance(call.filter, SymbolRef)
                mask_ch = lay[call.filter.name]
            specs.append(AggSpec(call.name, input_ch, in_type, mask_ch,
                                 call.distinct))

        partial_op = jax.jit(hash_aggregate(key_channels, specs, Step.PARTIAL))

        # FINAL consumes the partial layout: keys first, then each agg's
        # state columns in sequence
        from trino_tpu.ops.aggregate import get_aggregate
        nkeys = len(key_channels)
        state_channels = []
        ch = nkeys
        for spec in specs:
            fn = get_aggregate(spec.name, spec.input_type)
            k = len(fn.state(spec.input_type))
            state_channels.append(list(range(ch, ch + k)))
            ch += k
        final_keys = list(range(nkeys))
        final_op = jax.jit(hash_aggregate(final_keys, specs, Step.FINAL,
                                          state_channels))

        def gen():
            partials = []
            for page in src.pages:
                if int(page.num_rows) == 0:
                    continue
                partials.append(partial_op(page))
            if not partials:
                # empty input: global agg still emits one row
                if not key_channels:
                    yield self._empty_global_agg(node, specs)
                return
            merged = concat_pages(partials) if len(partials) > 1 \
                else partials[0]
            yield final_op(merged)
        return PageStream(gen(), node.outputs)

    def _empty_global_agg(self, node: AggregationNode, specs) -> Page:
        cols = []
        for (sym, call), spec in zip(node.aggregations, specs):
            typ = sym.type
            if call.name == "count":
                cols.append(Column(jnp.zeros(8, typ.dtype), None, typ, None))
            else:
                cols.append(Column(jnp.zeros(8, typ.dtype),
                                   jnp.zeros(8, dtype=jnp.bool_), typ, None))
        return Page(tuple(cols), 1)

    def _exec_GroupIdNode(self, node: GroupIdNode) -> PageStream:
        src = self.execute(node.source)
        lay, typ = _layout(src.symbols)
        out_syms = node.outputs
        all_group = tuple(dict.fromkeys(
            s for gs in node.grouping_sets for s in gs))

        def gen():
            for page in src.pages:
                for set_idx, gset in enumerate(node.grouping_sets):
                    in_set = {s.name for s in gset}
                    cols = []
                    for sym in all_group + node.passthrough:
                        c = page.column(lay[sym.name])
                        if sym in all_group and sym.name not in in_set:
                            # null out keys excluded from this grouping set
                            c = Column(c.values,
                                       jnp.zeros(page.capacity, jnp.bool_),
                                       c.type, c.dictionary)
                        cols.append(c)
                    gid = Column(
                        jnp.full(page.capacity, set_idx, dtype=jnp.int64),
                        None, T.BIGINT, None)
                    cols.append(gid)
                    yield Page(tuple(cols), page.num_rows)
        return PageStream(gen(), out_syms)

    def _exec_SortNode(self, node: SortNode) -> PageStream:
        src = self.execute(node.source)
        lay, _ = _layout(src.symbols)
        keys = [SortKey(lay[o.symbol.name], o.ascending, o.nulls_first)
                for o in node.order_by]

        def gen():
            page = self._collect(PageStream(src.pages, src.symbols))
            if page is None:
                return
            yield jax.jit(order_by(keys))(page)
        return PageStream(gen(), src.symbols)

    def _exec_TopNNode(self, node: TopNNode) -> PageStream:
        src = self.execute(node.source)
        lay, _ = _layout(src.symbols)
        keys = [SortKey(lay[o.symbol.name], o.ascending, o.nulls_first)
                for o in node.order_by]
        per_page = jax.jit(top_n(node.count, keys))

        def gen():
            # partial top-n per page bounds the concat size at
            # count * n_pages (GroupedTopN-builder analog)
            partials = []
            for page in src.pages:
                if int(page.num_rows) == 0:
                    continue
                partials.append(per_page(page))
            if not partials:
                return
            merged = concat_pages(partials) if len(partials) > 1 \
                else partials[0]
            yield jax.jit(top_n(node.count, keys))(merged)
        return PageStream(gen(), src.symbols)

    def _exec_JoinNode(self, node: JoinNode) -> PageStream:
        if node.kind == JoinKind.CROSS and not node.criteria:
            return self._exec_cross_join(node)
        if node.kind in (JoinKind.RIGHT, JoinKind.FULL):
            raise ExecutionError(f"{node.kind} join execution not supported "
                                 "yet")
        probe_stream = self.execute(node.left)
        build_stream = self.execute(node.right)
        probe_lay, probe_typ = _layout(probe_stream.symbols)
        build_lay, _ = _layout(build_stream.symbols)
        probe_keys = [probe_lay[c.left.name] for c in node.criteria]
        build_keys = [build_lay[c.right.name] for c in node.criteria]
        build_page = self._collect(build_stream)
        out_symbols = node.left.outputs + node.right.outputs
        join_kind = JoinType.INNER if node.kind == JoinKind.INNER \
            else JoinType.LEFT

        # residual non-equi filter evaluated over joined layout — valid for
        # INNER only (LEFT would wrongly drop null-extended rows; planner
        # rejects such plans)
        post_filter = None
        if node.filter is not None:
            if join_kind != JoinType.INNER:
                raise ExecutionError(
                    "non-inner join with residual filter not supported")
            lay, typ = _layout(out_symbols)
            post_filter = compile_filter(lower_expr(node.filter, lay, typ))

        def gen():
            nonlocal build_page
            if build_page is None:
                if join_kind == JoinType.INNER:
                    return
                # LEFT join with empty build: emit null-extended probe rows
                build_page = self._null_build_page(node.right.outputs)
            cap0 = self.page_capacity
            ops: Dict[int, object] = {}
            for probe_page in probe_stream.pages:
                if int(probe_page.num_rows) == 0:
                    continue
                cap = max(cap0, probe_page.capacity)
                while True:
                    if cap not in ops:
                        op = hash_join(probe_keys, build_keys, join_kind,
                                       output_capacity=cap)
                        if post_filter is None:
                            ops[cap] = jax.jit(
                                lambda p, b, o=op: o(p, b))
                        else:
                            def run(p, b, o=op):
                                out, total = o(p, b)
                                out = out.filter(post_filter(out))
                                return out, total
                            ops[cap] = jax.jit(run)
                    out, total = ops[cap](probe_page, build_page)
                    if int(total) <= cap:
                        break
                    cap = _next_pow2(int(total))  # re-run bigger (SURVEY §7)
                if int(out.num_rows) > 0:
                    yield out
        return PageStream(gen(), out_symbols)

    def _null_build_page(self, symbols: Tuple[Symbol, ...]) -> Page:
        cols = []
        for s in symbols:
            cols.append(Column(jnp.zeros(8, dtype=s.type.dtype),
                               jnp.zeros(8, dtype=jnp.bool_), s.type, None))
        return Page(tuple(cols), 0)

    def _exec_cross_join(self, node: JoinNode) -> PageStream:
        probe_stream = self.execute(node.left)
        build_stream = self.execute(node.right)
        build_page = self._collect(build_stream)
        out_symbols = node.left.outputs + node.right.outputs

        def gen():
            if build_page is None:
                return
            nb = int(build_page.num_rows)
            if nb == 1:
                # scalar-subquery path: broadcast the single build row
                def attach(p):
                    bcols = tuple(
                        Column(jnp.broadcast_to(c.values[:1], (p.capacity,)),
                               None if c.valid is None else
                               jnp.broadcast_to(c.valid[:1], (p.capacity,)),
                               c.type, c.dictionary)
                        for c in build_page.columns)
                    return Page(tuple(p.columns) + bcols, p.num_rows)
                run = jax.jit(attach)
                for page in probe_stream.pages:
                    if int(page.num_rows):
                        yield run(page)
                return
            # general cross join: bounded expansion
            for page in probe_stream.pages:
                np_rows = int(page.num_rows)
                if np_rows == 0:
                    continue
                total = np_rows * nb
                if total > 4 * 1024 * 1024:
                    raise ExecutionError(
                        f"cross join too large ({total} rows)")
                cap = _next_pow2(total)
                idx = jnp.arange(cap, dtype=jnp.int32)
                pi = jnp.minimum(idx // nb, page.capacity - 1)
                bi = jnp.minimum(idx % nb, build_page.capacity - 1)
                pcols = tuple(c.gather(pi) for c in page.columns)
                bcols = tuple(c.gather(bi) for c in build_page.columns)
                yield Page(pcols + bcols, total)
        return PageStream(gen(), out_symbols)

    def _exec_semijoin_filter(self, node: FilterNode) -> PageStream:
        semi: SemiJoinNode = node.source
        match_name = semi.match_symbol.name
        mode: Optional[str] = None
        rest: List[RowExpression] = []
        from trino_tpu.planner.optimizer import conjuncts, combine
        for c in conjuncts(node.predicate):
            if isinstance(c, SymbolRef) and c.name == match_name:
                mode = "semi"
            elif isinstance(c, SpecialForm) and c.kind is SpecialKind.NOT \
                    and isinstance(c.args[0], SymbolRef) \
                    and c.args[0].name == match_name:
                mode = "anti"
            elif match_name in _symbol_names(c):
                raise ExecutionError(
                    "complex semi-join match usage not supported")
            else:
                rest.append(c)
        if mode is None:
            raise ExecutionError("semi-join match symbol unused in filter")

        probe_stream = self.execute(semi.source)
        build_stream = self.execute(semi.filtering_source)
        probe_lay, probe_typ = _layout(probe_stream.symbols)
        build_lay, _ = _layout(build_stream.symbols)
        probe_keys = [probe_lay[s.name] for s in semi.source_keys]
        build_keys = [build_lay[s.name] for s in semi.filtering_keys]
        build_page = self._collect(build_stream)
        jt = JoinType.SEMI if mode == "semi" else JoinType.ANTI
        rest_pred = combine(rest)
        rest_fn = None
        if rest_pred is not None:
            rest_fn = compile_filter(
                lower_expr(rest_pred, probe_lay, probe_typ))

        def gen():
            bp = build_page
            if bp is None:
                if jt == JoinType.SEMI:
                    return
                bp = self._null_build_page(semi.filtering_source.outputs)
            ops: Dict[int, object] = {}
            for page in probe_stream.pages:
                if int(page.num_rows) == 0:
                    continue
                cap = max(self.page_capacity, page.capacity)
                while True:
                    if cap not in ops:
                        op = hash_join(probe_keys, build_keys, jt,
                                       output_capacity=cap)

                        def run(p, b, o=op):
                            out, total = o(p, b)
                            if rest_fn is not None:
                                out = out.filter(rest_fn(out))
                            return out, total
                        ops[cap] = jax.jit(run)
                    out, total = ops[cap](page, bp)
                    if int(total) <= cap:
                        break
                    cap = _next_pow2(int(total))
                if int(out.num_rows) > 0:
                    yield out
        return PageStream(gen(), semi.source.outputs)

    def _exec_SemiJoinNode(self, node: SemiJoinNode) -> PageStream:
        raise ExecutionError(
            "bare SemiJoinNode (match symbol escaping into projections) "
            "not supported; expected Filter(match) above")

    def _exec_EnforceSingleRowNode(self, node) -> PageStream:
        src = self.execute(node.source)

        def gen():
            page = self._collect(PageStream(src.pages, src.symbols))
            if page is None:
                # zero rows -> one all-null row (EnforceSingleRowOperator)
                yield Page(self._null_build_page(node.outputs).columns, 1)
                return
            n = int(page.num_rows)
            if n > 1:
                raise ExecutionError(
                    "Scalar sub-query has returned multiple rows")
            yield page
        return PageStream(gen(), node.outputs)

    def _exec_UnionNode(self, node: UnionNode) -> PageStream:
        nsyms = len(node.symbols)

        def gen():
            # start every child and peek one page each: string columns from
            # different tables carry different dictionaries, and blocking
            # consumers (sort/agg/join build) concat across children — so
            # re-encode onto a shared union dictionary. Pages of one child
            # stream share a per-column dictionary, so one peek suffices.
            children = []
            for j, child in enumerate(node.children):
                stream = self.execute(child)
                lay, _ = _layout(stream.symbols)
                order = [lay[node.mappings[i][j].name] for i in range(nsyms)]
                it = iter(stream.pages)
                first = next(it, None)
                children.append([it, first, order])
            remaps = _union_dictionary_remaps(node.symbols, children)
            for it, first, order in children:
                for page in _chain_first(first, it):
                    if int(page.num_rows) == 0:
                        continue
                    cols = []
                    for i, ch in enumerate(order):
                        col = page.column(ch)
                        remap = remaps[i].get(id(col.dictionary)) \
                            if remaps[i] else None
                        if remap is not None:
                            table, union_dict = remap
                            codes = jnp.take(table,
                                             jnp.clip(col.values, 0),
                                             mode="clip")
                            col = Column(codes, col.valid, col.type,
                                         union_dict)
                        cols.append(col)
                    yield Page(tuple(cols), page.num_rows)
        return PageStream(gen(), node.symbols)

    def _exec_ExchangeNode(self, node: ExchangeNode) -> PageStream:
        # single-device execution: exchanges are pass-through (the
        # distributed executor lowers them to collectives)
        return self.execute(node.source)

    def _exec_WindowNode(self, node: WindowNode) -> PageStream:
        raise ExecutionError("window function execution lands with the "
                             "window operator (planned)")

    def _exec_OutputNode(self, node: OutputNode) -> PageStream:
        src = self.execute(node.source)
        lay, _ = _layout(src.symbols)
        order = [lay[s.name] for s in node.symbols]
        if order == list(range(len(src.symbols))):
            return PageStream(src.pages, node.symbols)

        def gen():
            for page in src.pages:
                yield Page(tuple(page.column(c) for c in order),
                           page.num_rows)
        return PageStream(gen(), node.symbols)

    def _exec_TableWriterNode(self, node: TableWriterNode) -> PageStream:
        src = self.execute(node.source)
        lay, _ = _layout(src.symbols)
        order = [lay[s.name] for s in node.column_symbols]
        conn = self.metadata.connector(node.catalog)
        sink = conn.page_sink(node.table)

        def gen():
            written = 0
            for page in src.pages:
                n = int(page.num_rows)
                if n == 0:
                    continue
                out = Page(tuple(page.column(c) for c in order), n)
                sink.append_page(out)
                written += n
            sink.finish()
            col = Column(jnp.asarray(np.array([written] * 8,
                                              dtype=np.int64)),
                         None, T.BIGINT, None)
            yield Page((col,), 1)
        return PageStream(gen(), node.outputs)


def _chain_first(first: Optional[Page], rest: Iterator[Page]) -> Iterator[Page]:
    if first is not None:
        yield first
    yield from rest


def _union_dictionary_remaps(symbols, children):
    """Per output column: None when all children already share a dictionary,
    else {id(child_dict): (code_remap_device_array, union_dictionary)}."""
    from trino_tpu.page import union_dictionaries
    remaps: List[Optional[Dict[int, tuple]]] = []
    for i, sym in enumerate(symbols):
        dicts = []
        for it, first, order in children:
            if first is None:
                continue
            d = first.column(order[i]).dictionary
            if d is not None:
                dicts.append(d)
        uniq = {id(d): d for d in dicts}
        if len(uniq) <= 1:
            remaps.append(None)
            continue
        union, tables = union_dictionaries(list(uniq.values()))
        remaps.append({did: (tbl, union)
                       for did, tbl in zip(uniq, tables)})
    return remaps


def _valid_arr(valid: List[bool], cap: int) -> Optional[jnp.ndarray]:
    if all(valid):
        return None
    arr = np.zeros(cap, dtype=bool)
    arr[:len(valid)] = valid
    return jnp.asarray(arr)


def _symbol_names(e: RowExpression) -> set:
    out = set()

    def visit(x):
        if isinstance(x, SymbolRef):
            out.add(x.name)
        for c in x.children():
            visit(c)
    visit(e)
    return out
