"""Process-wide query registry + lifecycle states.

Reference parity: execution/QueryTracker.java + QueryStateMachine.java —
every statement entering a runner is registered with a monotonically
assigned id and walks QUEUED -> RUNNING -> FINISHED | FAILED | CANCELED,
carrying the stats rollup (row count, wall time, error name, retry/fault
counters, resource group, memory-pool reservation/kill/leak counters)
that system.runtime.queries and the HTTP server surface.

Concurrency model (round 7): transitions arrive from MANY threads (the
server's executor pool runs queries concurrently while HTTP threads
cancel and page), so the registry lock guards membership and each
QueryInfo carries its own transition lock — the per-query CAS of the
reference's state machine. Illegal transitions (FINISHED -> RUNNING,
resurrecting a CANCELED query) raise instead of silently corrupting the
rollup; cancel keeps its race-tolerant first-writer-wins semantics.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import List, Optional

QUEUED = "QUEUED"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"
CANCELED = "CANCELED"

TERMINAL = (FINISHED, FAILED, CANCELED)

# QueryStateMachine's legal edges (terminal states have none)
_ALLOWED = {
    RUNNING: (QUEUED,),
    FINISHED: (RUNNING,),
    FAILED: (QUEUED, RUNNING),
    CANCELED: (QUEUED, RUNNING),
}


@dataclasses.dataclass
class QueryInfo:
    query_id: str
    state: str
    user: str
    query: str
    created: float
    started: Optional[float] = None
    ended: Optional[float] = None
    rows: int = 0
    error: Optional[str] = None
    error_name: Optional[str] = None
    retries: int = 0
    faults_injected: int = 0
    resource_group: Optional[str] = None
    # mesh shape the query executed over ("workers:8"); None for
    # single-device execution
    mesh: Optional[str] = None
    pool_peak_bytes: int = 0
    memory_kills: int = 0        # times the low-memory killer chose us
    leaked_bytes: int = 0        # nonzero ledger at successful end
    # observability rollup (obs/stats.py): HOST execution time (the
    # measured device and compile walls live in stats as
    # device_time_ms/compile_time_ms — cpu_time_ms stopped being
    # device-inclusive in round 13), output bytes, and the full
    # snapshot + span dump the runner stamps before the terminal
    # transition. trace_file is the exported Chrome-trace path when the
    # session ran with trace_export on.
    cpu_time_ms: int = 0
    output_bytes: int = 0
    stats: Optional[dict] = dataclasses.field(
        default=None, repr=False, compare=False)
    trace: Optional[dict] = dataclasses.field(
        default=None, repr=False, compare=False)
    trace_file: Optional[str] = None
    warnings: List[str] = dataclasses.field(default_factory=list)
    # the live memory context while executing (None before/after): lets
    # system.runtime.queries read the current pool reservation
    mem: Optional[object] = dataclasses.field(
        default=None, repr=False, compare=False)
    lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False)

    @property
    def wall_ms(self) -> Optional[int]:
        if self.started is None:
            return None
        end = self.ended if self.ended is not None else time.monotonic()
        return int((end - self.started) * 1000)

    @property
    def pool_reserved_bytes(self) -> int:
        ctx = self.mem
        return int(ctx.reserved) if ctx is not None else 0

    def _check_transition(self, to_state: str) -> None:
        """Validate an edge; the caller sets the stats fields and THEN
        publishes the state (readers don't take the per-info lock, so the
        terminal state must land last)."""
        if self.state not in _ALLOWED[to_state]:
            raise ValueError(
                f"illegal query state transition {self.state} -> "
                f"{to_state} for {self.query_id}")


class QueryTracker:
    def __init__(self, keep: int = 200):
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._queries: "dict[str, QueryInfo]" = {}
        self._keep = keep

    def begin(self, sql: str, user: str = "user",
              query_id: Optional[str] = None,
              resource_group: Optional[str] = None) -> QueryInfo:
        with self._lock:
            if query_id is not None and query_id in self._queries:
                # the HTTP server pre-registers at submit (QUEUED); the
                # runner's begin then adopts that entry instead of
                # double-counting the query
                return self._queries[query_id]
            qid = query_id or f"{time.strftime('%Y%m%d')}_{next(self._seq):06d}"
            info = QueryInfo(qid, QUEUED, user, sql, time.monotonic(),
                             resource_group=resource_group)
            self._queries[qid] = info
            # bound the registry (QueryTracker prunes expired queries)
            while len(self._queries) > self._keep:
                done = next((k for k, v in self._queries.items()
                             if v.state in TERMINAL), None)
                if done is None:
                    break
                del self._queries[done]
        # fire OUTSIDE the registry lock (QueryMonitor.queryCreatedEvent:
        # listeners may themselves consult the tracker)
        from trino_tpu.obs.listeners import fire_query_created
        fire_query_created(info)
        return info

    def running(self, info: QueryInfo) -> None:
        with info.lock:
            info._check_transition(RUNNING)
            info.started = time.monotonic()
            info.state = RUNNING

    def finish(self, info: QueryInfo, rows: int) -> None:
        with info.lock:
            info._check_transition(FINISHED)
            info.rows = rows
            info.ended = time.monotonic()
            info.state = FINISHED
        from trino_tpu.obs.listeners import fire_query_completed
        fire_query_completed(info)

    def fail(self, info: QueryInfo, error: str,
             error_name: Optional[str] = None) -> None:
        with info.lock:
            info._check_transition(FAILED)
            info.error = error
            info.error_name = error_name
            info.ended = time.monotonic()
            info.state = FAILED
        from trino_tpu.obs.listeners import fire_query_failed
        fire_query_failed(info)

    def cancel(self, info: QueryInfo,
               reason: str = "Query was canceled by user") -> None:
        with info.lock:
            if info.state in TERMINAL:
                return        # cancel raced a finish: first writer wins
            info._check_transition(CANCELED)
            info.error = reason
            info.error_name = "USER_CANCELED"
            info.ended = time.monotonic()
            info.state = CANCELED
        from trino_tpu.obs.listeners import fire_query_failed
        fire_query_failed(info)

    def list(self) -> List[QueryInfo]:
        with self._lock:
            return list(self._queries.values())


# the process-wide tracker (DiscoveryNodeManager-style singleton scope)
TRACKER = QueryTracker()
