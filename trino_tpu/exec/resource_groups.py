"""Resource groups: admission control + weighted-fair query scheduling.

Reference parity: execution/resourcegroups/InternalResourceGroup.java +
InternalResourceGroupManager.java:66 — a tree of named groups, each with
`max_queued` (admission: an over-limit submit fails QUERY_QUEUE_FULL),
`hard_concurrency` (cap on simultaneously running queries in the subtree),
`soft_memory_limit_bytes` (a group whose running queries hold this much of
the node pool admits no new query until usage drops), and a
`scheduling_weight` used for WEIGHTED_FAIR selection across siblings.

Scheduling is stride-based (the deterministic form of the reference's
WEIGHTED_FAIR policy) over WALL-CLOCK virtual time: every group carries
a virtual `pass` advanced by an estimated execution quantum per started
query (the group's EWMA slice, `avg_slice_s`) divided by its weight, and
reconciled against the MEASURED slice when the server charges the
finished execution's wall (`charge`). When an executor slot frees,
selection walks the tree picking the eligible child with the smallest
pass. Under saturation with equal-cost queries a 2:1-weighted sibling
pair therefore drains queries 2:1 — exactly, not just in expectation —
and with skewed costs the groups share executor SECONDS 2:1: a group
burning long queries yields slots to lighter siblings.

Group names are dotted paths ("adhoc.alice"); intermediate groups are
created on demand, and limits are enforced at EVERY level of the chain
(InternalResourceGroup.canQueueMore / canRunMore walk the ancestors).

The manager is the server's dispatch queue: `submit` enqueues, the
executor pool's workers block in `take`, and `finish` releases the slot.
A module-level registry of live managers backs
system.runtime.resource_groups.
"""

from __future__ import annotations

import collections
import threading
import time
import weakref
from typing import Deque, Dict, List, Optional, Tuple

DEFAULT_HARD_CONCURRENCY = 16
DEFAULT_MAX_QUEUED = 200

# live managers, for system.runtime.resource_groups (weak: a stopped
# server's manager disappears with it)
_MANAGERS: "weakref.WeakSet[ResourceGroupManager]" = weakref.WeakSet()


class ResourceGroup:
    """One node of the group tree. Counters are guarded by the owning
    manager's condition lock."""

    def __init__(self, name: str, parent: Optional["ResourceGroup"] = None,
                 hard_concurrency: int = DEFAULT_HARD_CONCURRENCY,
                 max_queued: int = DEFAULT_MAX_QUEUED,
                 soft_memory_limit_bytes: Optional[int] = None,
                 weight: int = 1):
        self.name = name                      # full dotted path
        self.parent = parent
        self.children: Dict[str, ResourceGroup] = {}
        self.hard_concurrency = int(hard_concurrency)
        self.max_queued = int(max_queued)
        self.soft_memory_limit_bytes = soft_memory_limit_bytes
        self.weight = max(1, int(weight))
        self.queue: Deque[Tuple[object, str]] = collections.deque()
        self.queued = 0          # subtree queued count (incl. own queue)
        self.running: set = set()  # subtree running query ids
        self.started = 0
        self.finished = 0
        # completed queries the serving tier answered from the result
        # cache WITHOUT dispatching (the POST-time fast path): they
        # consume no executor slot but they ARE this group's traffic —
        # group QPS quotas and dashboards must see them. Counted into
        # started/finished too, with this column splitting out how many
        # of those completions were zero-cost.
        self.served_from_cache = 0
        # per-group QPS quota on that fast path (round 14): a token
        # bucket refilled at `result_cache_qps` tokens/s up to
        # `result_cache_qps_burst`; an over-quota hit is REJECTED with
        # QUERY_QUEUE_FULL on the wire instead of served — the
        # enforcement half of the served_from_cache accounting. None =
        # unlimited. Enforced at every level of the chain.
        self.result_cache_qps: Optional[float] = None
        self.result_cache_qps_burst: Optional[float] = None
        self._rc_tokens = 0.0
        self._rc_stamp: Optional[float] = None
        self.cache_hit_rejections = 0
        self.scheduled_wall_s = 0.0   # execution wall charged to subtree
        # EWMA of observed execution-slice wall: the stride quantum a
        # start pre-charges (reconciled by `charge` when the real slice
        # is known) — keeps pass wall-denominated so sub-second and
        # multi-second statements compete in the same units
        self.avg_slice_s = 0.1
        self._pass = 0.0         # stride virtual time (seconds / weight)

    def memory_usage(self) -> int:
        """Node-pool bytes currently held by this subtree's running
        queries (the soft_memory_limit denominator)."""
        from trino_tpu.exec.memory import NODE_POOL
        return sum(NODE_POOL.reserved_of(qid) for qid in self.running)

    def _chain(self) -> List["ResourceGroup"]:
        out, g = [], self
        while g is not None:
            out.append(g)
            g = g.parent
        return out


class ResourceGroupManager:
    """The group tree + the dispatch queue the server's executor pool
    drains (InternalResourceGroupManager + the dispatcher's queue)."""

    def __init__(self, default_hard_concurrency: int =
                 DEFAULT_HARD_CONCURRENCY,
                 default_max_queued: int = DEFAULT_MAX_QUEUED,
                 max_total_queued: Optional[int] = None,
                 max_groups: int = 64):
        self._cond = threading.Condition()
        self.default_hard_concurrency = default_hard_concurrency
        self.default_max_queued = default_max_queued
        # manager-wide admission bound (the round-5 global queue bound):
        # per-group max_queued alone would let a client mint fresh
        # groups, each with its own budget
        self.max_total_queued = max_total_queued
        # cap on CLIENT-minted groups (submit with an unknown name):
        # beyond it, unknown names route to "global" instead of growing
        # server state without bound from untrusted header input
        self.max_groups = max_groups
        self._top: Dict[str, ResourceGroup] = {}
        self._by_name: Dict[str, ResourceGroup] = {}
        # per-query record of the slice estimates take() pre-charged
        # (group name -> estimate, one per chain level): charge() must
        # reconcile against the estimate that was ACTUALLY charged, not
        # the current EWMA — with concurrent queries in one group the
        # EWMA moves between take and charge, and reconciling against
        # the moved value would systematically mis-charge the group
        self._precharged: Dict[str, Dict[str, float]] = {}
        _MANAGERS.add(self)

    # ------------------------------------------------------------ the tree

    def get_or_create(self, name: str, **config) -> ResourceGroup:
        with self._cond:
            return self._get_or_create_locked(name, **config)

    def _get_or_create_locked(self, name: str, **config) -> ResourceGroup:
        name = name.strip() or "global"
        g = self._by_name.get(name)
        if g is not None:
            if config:
                self._configure_locked(g, **config)
            return g
        parent = None
        if "." in name:
            parent = self._get_or_create_locked(name.rsplit(".", 1)[0])
        g = ResourceGroup(
            name, parent,
            hard_concurrency=config.pop("hard_concurrency",
                                        self.default_hard_concurrency),
            max_queued=config.pop("max_queued", self.default_max_queued),
            soft_memory_limit_bytes=config.pop("soft_memory_limit_bytes",
                                               None),
            weight=config.pop("weight", 1))
        siblings = self._top if parent is None else parent.children
        # a newcomer joins at the CURRENT virtual time, not pass 0 —
        # otherwise a group created late monopolizes slots until it
        # catches up with long-lived siblings (stride-scheduler rule)
        g._pass = min((s._pass for s in siblings.values()), default=0.0)
        self._by_name[name] = g
        siblings[name] = g
        return g

    def configure(self, name: str, **config) -> ResourceGroup:
        """Create-or-update a group's limits (the file-based
        ResourceGroupConfigurationManager analog, driven from code)."""
        with self._cond:
            g = self._get_or_create_locked(name)
            self._configure_locked(g, **config)
            self._cond.notify_all()
            return g

    # -------------------------------------------------- file-based config

    def configure_from_dict(self, tree) -> None:
        """Build the group tree a FileResourceGroupConfigurationManager
        JSON describes: `{"groups"|"rootGroups": [{"name",
        "hard_concurrency"|"hardConcurrencyLimit",
        "max_queued"|"maxQueued",
        "scheduling_weight"|"schedulingWeight", "soft_memory_limit"|
        "softMemoryLimit" (bytes, a '512MB'-style size, or '10%' of the
        node pool), "subgroups"|"subGroups": [...]}, ...]}` — the same
        tree `configure` builds in code, with the reference's camelCase
        field names accepted so its documented examples load unmodified.
        A top-level bare list also works; anything else is an error (a
        typo'd wrapper key must not silently configure ZERO groups on a
        server the operator believes is limited)."""
        if isinstance(tree, list):
            groups = tree
        else:
            groups = tree.get("groups", tree.get("rootGroups"))
            if groups is None:
                raise ValueError(
                    "resource group config needs a top-level 'groups' or "
                    f"'rootGroups' list (got keys: {sorted(tree)})")
        visited: set = set()
        for spec in groups:
            self._configure_group_spec(spec, prefix="", visited=visited)
        # quotas are DECLARATIVE all the way: a group whose spec was
        # REMOVED from the file must lose its quota too (a hot reload
        # that drops the group entirely means 'unlimited', matching the
        # fleet workers' rebuilt-from-scratch quota map). Other limits
        # keep their last configured values — they have safe in-code
        # defaults; a lingering quota keeps rejecting users.
        with self._cond:
            for g in self._by_name.values():
                if g.name not in visited and \
                        g.result_cache_qps is not None:
                    g.result_cache_qps = None
                    g.result_cache_qps_burst = None

    def _configure_group_spec(self, spec: dict, prefix: str,
                              visited: Optional[set] = None) -> None:
        name = str(spec.get("name", "")).strip()
        if not name:
            raise ValueError("resource group spec without a name")
        full = f"{prefix}.{name}" if prefix else name
        if visited is not None:
            visited.add(full)
        known = {"name", "subgroups", "subGroups",
                 "hard_concurrency", "hardConcurrencyLimit",
                 "max_queued", "maxQueued",
                 "weight", "scheduling_weight", "schedulingWeight",
                 "soft_memory_limit", "softMemoryLimit",
                 "soft_memory_limit_bytes",
                 "result_cache_qps", "resultCacheQps",
                 "result_cache_qps_burst", "resultCacheQpsBurst",
                 # reference keys with no engine counterpart yet —
                 # tolerated (valid config, unimplemented feature), NOT
                 # typos: scheduling here is always weighted-fair and
                 # metrics export is always on
                 "schedulingPolicy", "scheduling_policy", "jmxExport"}
        unknown = sorted(set(spec) - known)
        if unknown:
            # same strictness as the wrapper key: a typo'd limit must not
            # silently leave the group at permissive defaults
            raise ValueError(
                f"resource group {full!r}: unknown config keys {unknown}")
        config = {}
        for key, aliases in (
                ("hard_concurrency", ("hardConcurrencyLimit",)),
                ("max_queued", ("maxQueued",)),
                ("weight", ("scheduling_weight", "schedulingWeight"))):
            for k in (key,) + aliases:
                if k in spec:
                    try:
                        config[key] = int(spec[k])
                    except (TypeError, ValueError) as e:
                        raise ValueError(
                            f"resource group {full!r}: bad {k} value "
                            f"{spec[k]!r}: {e}") from e
                    break
        for key, aliases in (
                ("result_cache_qps", ("resultCacheQps",)),
                ("result_cache_qps_burst", ("resultCacheQpsBurst",))):
            # quota config is DECLARATIVE per spec: an absent key means
            # unlimited, so a hot-reload that deletes the quota clears
            # it here exactly like the workers' rebuilt-from-scratch
            # quota map does — the fleet cannot split-brain on a removal
            config[key] = None
            for k in (key,) + aliases:
                if k in spec:
                    try:
                        config[key] = float(spec[k])
                    except (TypeError, ValueError) as e:
                        raise ValueError(
                            f"resource group {full!r}: bad {k} value "
                            f"{spec[k]!r}: {e}") from e
                    break
        for k in ("soft_memory_limit", "softMemoryLimit",
                  "soft_memory_limit_bytes"):
            if k in spec:
                from trino_tpu.exec.memory import NODE_POOL
                try:
                    config["soft_memory_limit_bytes"] = parse_data_size(
                        spec[k], percent_of=NODE_POOL.limit)
                except (TypeError, ValueError) as e:
                    raise ValueError(
                        f"resource group {full!r}: bad {k} value "
                        f"{spec[k]!r}: {e}") from e
                break
        self.configure(full, **config)
        for sub in spec.get("subgroups", spec.get("subGroups", [])):
            self._configure_group_spec(sub, prefix=full, visited=visited)

    @classmethod
    def from_file(cls, path: str, **manager_kwargs) -> "ResourceGroupManager":
        """Manager preconfigured from a JSON file (the server's
        `resource_groups.path` option)."""
        import json
        with open(path) as f:
            tree = json.load(f)
        mgr = cls(**manager_kwargs)
        mgr.configure_from_dict(tree)
        return mgr

    @staticmethod
    def _configure_locked(g: ResourceGroup, **config) -> None:
        for key in ("hard_concurrency", "max_queued", "weight"):
            if key in config:
                setattr(g, key, max(0, int(config.pop(key))) if
                        key != "weight" else max(1, int(config.pop(key))))
        if "soft_memory_limit_bytes" in config:
            g.soft_memory_limit_bytes = config.pop("soft_memory_limit_bytes")
        if "result_cache_qps" in config:
            g.result_cache_qps = config.pop("result_cache_qps")
        if "result_cache_qps_burst" in config:
            g.result_cache_qps_burst = config.pop("result_cache_qps_burst")
        if config:
            raise TypeError(f"unknown resource group config: {config}")

    def groups(self) -> List[ResourceGroup]:
        with self._cond:
            return sorted(self._by_name.values(), key=lambda g: g.name)

    # -------------------------------------------------------- the dispatch

    def submit(self, group_name: str, item: object, query_id: str) -> bool:
        """Admit + enqueue. False = some level of the chain (or the
        manager-wide bound) is at max_queued — the caller surfaces
        QUERY_QUEUE_FULL."""
        with self._cond:
            if self.max_total_queued is not None and sum(
                    t.queued for t in self._top.values()
            ) >= self.max_total_queued:
                return False
            if group_name.strip() not in self._by_name \
                    and len(self._by_name) >= self.max_groups:
                group_name = "global"   # don't mint unbounded groups
            g = self._get_or_create_locked(group_name)
            for a in g._chain():
                if a.queued >= a.max_queued:
                    return False
            g.queue.append((item, query_id))
            for a in g._chain():
                a.queued += 1
            self._cond.notify_all()
            return True

    def take(self, timeout: Optional[float] = None
             ) -> Optional[Tuple[ResourceGroup, object]]:
        """Block until some eligible group has a queued item; pop it by
        weighted-fair selection and mark it running. None on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                leaf = self._pick_locked()
                if leaf is not None:
                    item, qid = leaf.queue.popleft()
                    est: Dict[str, float] = {}
                    for a in leaf._chain():
                        a.queued -= 1
                        a.running.add(qid)
                        a.started += 1
                        # pre-charge the estimated quantum (stride with
                        # estimated slices): without it, every take
                        # between two charges would pick the same group
                        est[a.name] = a.avg_slice_s
                        a._pass += a.avg_slice_s / a.weight
                    self._precharged[qid] = est
                    return leaf, item
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._cond.wait(remaining)
                else:
                    self._cond.wait()

    def finish(self, group: ResourceGroup, query_id: str) -> None:
        with self._cond:
            # un-charged queries (direct manager users) drop their
            # pre-charge record here; charged ones already popped it
            self._precharged.pop(query_id, None)
            for a in group._chain():
                a.running.discard(query_id)
                a.finished += 1
            self._cond.notify_all()

    def record_cache_hit_rejection(self, group_name: str,
                                   n: int = 1) -> None:
        """Account quota rejections that were ENFORCED elsewhere (the
        fleet's shared-memory buckets — worker-side or the engine's
        fast_path_quota seam): the group's rejection counters must read
        true fleet-wide even though no in-process bucket fired."""
        with self._cond:
            if group_name.strip() not in self._by_name \
                    and len(self._by_name) >= self.max_groups:
                group_name = "global"
            g = self._get_or_create_locked(group_name)
            g.cache_hit_rejections += n

    def record_cache_hit(self, group_name: str, n: int = 1,
                         enforce: bool = True) -> Optional[ResourceGroup]:
        """Account `n` result-cache fast-path completions to the group
        chain: the POST-time hit bypasses submit/take/finish entirely
        (zero executor cost to admit — that stays true), but without
        this the group's completed-query counters would under-read its
        real traffic and a group QPS quota would never see cached load.
        No stride/pass movement: the hit consumed no executor wall.

        With `enforce` (the default), every chain level with a
        configured `result_cache_qps` must grant a token from its
        bucket FIRST; an over-quota hit returns None — nothing is
        counted except the rejection — and the caller answers
        QUERY_QUEUE_FULL instead of the cached data. `enforce=False` is
        the accounting-only path for hits whose quota was already
        checked elsewhere (the fleet's workers check the SHARED bucket
        before serving; the engine then ingests their counts)."""
        now = time.monotonic()
        with self._cond:
            if group_name.strip() not in self._by_name \
                    and len(self._by_name) >= self.max_groups:
                group_name = "global"   # same bound as submit(): an
                # untrusted header name must not mint server state
            g = self._get_or_create_locked(group_name)
            chain = g._chain()
            if enforce:
                for a in chain:
                    if not self._rc_bucket_take_locked(a, now, float(n)):
                        for b in chain:     # refund the levels already
                            if b is a:      # charged (all-or-nothing)
                                break
                            if b.result_cache_qps is not None:
                                b._rc_tokens += float(n)
                        a.cache_hit_rejections += n
                        return None
            for a in chain:
                a.started += n
                a.finished += n
                a.served_from_cache += n
            return g

    @staticmethod
    def _rc_bucket_take_locked(g: ResourceGroup, now: float,
                               n: float) -> bool:
        rate = g.result_cache_qps
        if rate is None:
            return True
        burst = g.result_cache_qps_burst \
            if g.result_cache_qps_burst is not None else max(rate, 1.0)
        if g._rc_stamp is None:
            g._rc_tokens = burst
            g._rc_stamp = now
        else:
            # `now` was read BEFORE the caller took the manager lock: a
            # loser of the lock race can arrive with now < _rc_stamp,
            # and an unclamped negative delta would drain tokens and
            # rewind the stamp (double-crediting the next caller)
            elapsed = max(0.0, now - g._rc_stamp)
            g._rc_tokens = min(burst, g._rc_tokens + elapsed * rate)
            g._rc_stamp = max(g._rc_stamp, now)
        if g._rc_tokens < n:
            return False
        g._rc_tokens -= n
        return True

    def charge(self, group: ResourceGroup, seconds: float,
               query_id: Optional[str] = None) -> None:
        """Per-group weighted CPU scheduling (the split-scheduler's
        weighted share, collapsed to the single-controller engine):
        account a finished execution slice's wall to the group chain and
        reconcile the stride pass — the start pre-charged an ESTIMATED
        quantum, so the correction is (measured - estimate)/weight, and
        the estimate itself updates (EWMA) for the next pre-charge.
        `query_id` recovers the estimate that was ACTUALLY pre-charged
        at take (the EWMA may have moved since, and reconciling against
        the moved value would mis-charge concurrent same-group queries);
        without it the current EWMA approximates. Net effect: pass
        advances by MEASURED seconds/weight per query, so the next
        `take` favors groups that have consumed less executor wall per
        unit weight — not just started fewer queries. With equal-cost
        queries this reduces to the exact 2:1 start drain; with skewed
        costs a group burning long queries yields slots to lighter
        siblings proportionally to weight."""
        if group is None or seconds <= 0:
            return
        with self._cond:
            pre = self._precharged.pop(query_id, None) \
                if query_id is not None else None
            for a in group._chain():
                estimate = a.avg_slice_s if pre is None \
                    else pre.get(a.name, a.avg_slice_s)
                a.scheduled_wall_s += seconds
                a._pass += (seconds - estimate) / a.weight
                a.avg_slice_s += 0.2 * (seconds - a.avg_slice_s)
            self._cond.notify_all()

    # ------------------------------------------------- weighted-fair pick

    def _eligible_locked(self, g: ResourceGroup) -> bool:
        if g.queued == 0:
            return False
        if len(g.running) >= g.hard_concurrency:
            return False
        lim = g.soft_memory_limit_bytes
        if lim is not None and g.memory_usage() >= lim:
            return False
        return True

    def _pick_locked(self) -> Optional[ResourceGroup]:
        """Smallest pass-vector (root-to-leaf) among groups whose own
        queue is nonempty and whose whole ancestor chain can run — the
        lexicographic form of recursive stride descent, with correct
        backtracking past subtrees blocked deeper down."""
        best = best_key = None
        for g in self._by_name.values():
            if not g.queue:
                continue
            chain = g._chain()               # leaf .. root
            if any(not self._eligible_locked(a) for a in chain):
                continue
            key = tuple((a._pass, a.name) for a in reversed(chain))
            if best is None or key < best_key:
                best, best_key = g, key
        return best


def parse_data_size(value, percent_of: Optional[int] = None
                    ) -> Optional[int]:
    """'512MB' / '1.5GB' / '10%' / bare bytes -> int bytes (io.airlift
    DataSize grammar plus the percentage form the reference's
    softMemoryLimit examples use; units match case-insensitively). A
    percentage resolves against `percent_of` (the node pool limit); with
    no bound to take a percentage of, it means "no limit" (None)."""
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return int(value)
    text = str(value).strip()
    if text.endswith("%"):
        fraction = float(text[:-1].strip()) / 100.0
        if percent_of is None:
            return None
        return int(percent_of * fraction)
    units = {"b": 1, "kb": 1 << 10, "mb": 1 << 20, "gb": 1 << 30,
             "tb": 1 << 40, "pb": 1 << 50}
    lowered = text.lower()
    for unit in sorted(units, key=len, reverse=True):
        if lowered.endswith(unit):
            return int(float(text[:-len(unit)].strip()) * units[unit])
    return int(float(text))


def list_all_groups() -> List[ResourceGroup]:
    """Every live manager's groups (system.runtime.resource_groups)."""
    out: List[ResourceGroup] = []
    for mgr in list(_MANAGERS):
        out.extend(mgr.groups())
    return sorted(out, key=lambda g: g.name)
