"""Closed-loop QPS benchmark: N clients hammer prepared EXECUTEs over
HTTP.

The serving tier's acceptance instrument (`bench.py --qps`): start a
TrinoServer over the tiny TPC-H catalog, warm it through the warmup
manifest (PREPARE + one priming EXECUTE per parameter value), then run
`clients` closed-loop threads — each POSTs `EXECUTE qps_probe USING k`
on a persistent HTTP connection, follows `nextUri` when present, and
immediately issues the next request. Reported: sustained completed
executions/second over the measurement window, latency percentiles,
cache hit rates, and the zero-work proof for cache hits (a sampled hit's
stats read planning_s == 0, jit_misses == 0, execution_s == 0).

Closed-loop means throughput is the system's, not the generator's: every
client always has exactly one request in flight, so sustained QPS =
completed / window with per-request latency the full POST->FINISHED
round trip.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from typing import Any, Dict, List, Optional

PROBE_NAME = "qps_probe"
PROBE_SQL = ("SELECT n_name, n_regionkey FROM nation "
             "WHERE n_nationkey = ?")
PROBE_VALUES = 25     # nation keys 0..24


def _client_loop(host: str, port: int, idx: int, stop_at: List[float],
                 measure_from: List[float], latencies: List[float],
                 counters: Dict[str, int], lock: threading.Lock) -> None:
    conn = http.client.HTTPConnection(host, port)
    n = 0
    try:
        while time.monotonic() < stop_at[0]:
            value = (idx * 7 + n) % PROBE_VALUES
            n += 1
            t0 = time.monotonic()
            try:
                conn.request(
                    "POST", "/v1/statement",
                    body=f"EXECUTE {PROBE_NAME} USING {value}",
                    headers={"X-Trino-User": f"qps-{idx}"})
                resp = conn.getresponse()
                payload = json.loads(resp.read())
                while "nextUri" in payload:
                    path = payload["nextUri"].split(f":{port}", 1)[1]
                    conn.request("GET", path)
                    resp = conn.getresponse()
                    payload = json.loads(resp.read())
                ok = payload["stats"]["state"] == "FINISHED" \
                    and "error" not in payload
            except Exception:   # noqa: BLE001 — count, reconnect, go on
                ok = False
                conn.close()
                conn = http.client.HTTPConnection(host, port)
            dt = time.monotonic() - t0
            with lock:
                if t0 >= measure_from[0]:
                    if ok:
                        latencies.append(dt)
                        counters["completed"] += 1
                    else:
                        counters["errors"] += 1
    finally:
        conn.close()


def _percentile(sorted_vals: List[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(p * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def run_qps_bench(duration_s: float = 8.0, clients: int = 8,
                  warmup_s: float = 1.0, max_running: int = 4,
                  server=None) -> Dict[str, Any]:
    """Run the closed loop and return the report dict. A caller-provided
    `server` (tests) is used as-is and NOT stopped; otherwise a fresh
    tiny-TPC-H server starts, warms via the manifest, and stops after."""
    from trino_tpu.exec.plan_cache import stats as plan_stats
    from trino_tpu.serve.caches import result_cache_stats

    own_server = server is None
    if own_server:
        from trino_tpu.exec import LocalQueryRunner
        from trino_tpu.server import TrinoServer
        manifest = {"statements": [
            # PREPARE + one priming EXECUTE: plan cache + kernels warm
            {"name": PROBE_NAME, "sql": PROBE_SQL, "using": "0"},
        ]}
        server = TrinoServer(
            LocalQueryRunner.tpch("tiny"), max_running=max_running,
            query_timeout_s=60, warmup_manifest=manifest).start()
    try:
        host, port = "127.0.0.1", server.port
        # prime every parameter value once so the measurement window is
        # the steady state (result-cache hits), not first-touch misses
        conn = http.client.HTTPConnection(host, port)
        for value in range(PROBE_VALUES):
            conn.request("POST", "/v1/statement",
                         body=f"EXECUTE {PROBE_NAME} USING {value}",
                         headers={"X-Trino-User": "qps-prime"})
            payload = json.loads(conn.getresponse().read())
            while "nextUri" in payload:
                conn.request("GET",
                             payload["nextUri"].split(f":{port}", 1)[1])
                payload = json.loads(conn.getresponse().read())
        conn.close()

        plan_before = plan_stats()
        result_before = result_cache_stats()
        now = time.monotonic()
        measure_from = [now + warmup_s]
        stop_at = [now + warmup_s + duration_s]
        latencies: List[float] = []
        counters = {"completed": 0, "errors": 0}
        lock = threading.Lock()
        threads = [threading.Thread(
            target=_client_loop,
            args=(host, port, i, stop_at, measure_from, latencies,
                  counters, lock), daemon=True)
            for i in range(clients)]
        t_start = time.monotonic()
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=warmup_s + duration_s + 60)
        window = max(time.monotonic() - t_start - warmup_s, 1e-6)
        window = min(window, duration_s + 5.0)

        result_after = result_cache_stats()
        plan_after = plan_stats()
        hits = result_after["hits"] - result_before["hits"]
        misses = result_after["misses"] - result_before["misses"]
        lat = sorted(latencies)
        report: Dict[str, Any] = {
            "clients": clients,
            "duration_s": round(window, 2),
            "completed": counters["completed"],
            "errors": counters["errors"],
            "qps": round(counters["completed"] / window, 1),
            "p50_ms": round(_percentile(lat, 0.50) * 1000, 2),
            "p95_ms": round(_percentile(lat, 0.95) * 1000, 2),
            "p99_ms": round(_percentile(lat, 0.99) * 1000, 2),
            "result_cache_hit_rate": round(
                hits / max(hits + misses, 1), 4),
            "plan_cache_hits_delta":
                plan_after["hits"] - plan_before["hits"],
        }
        # the zero-work proof: sample a measurement-window cache hit's
        # stats from the tracker — planning, jit, and operator execution
        # must all read zero for a result served from cache
        from trino_tpu.exec.query_tracker import TRACKER
        sample = next(
            (q.stats for q in reversed(TRACKER.list())
             if q.stats and q.stats.get("result_cache_hits")), None)
        if sample is not None:
            report["cache_hit_zero_planning"] = \
                sample.get("planning_s", 1) == 0
            report["cache_hit_zero_jit"] = \
                sample.get("jit_misses", 1) == 0
            report["cache_hit_zero_execution"] = \
                sample.get("execution_s", 1) == 0
        if own_server:
            report["warmup_report"] = server.warmup_report
        return report
    finally:
        if own_server:
            server.stop()
