"""Plan sanity checker run between optimizer stages.

Reference parity: sql/planner/sanity/PlanSanityChecker.java (+
ValidateDependenciesChecker.java:66): every symbol an expression
references must be produced by the node's children, output symbol names
must be unique per node, and join criteria sides must come from the
correct child. Catches optimizer-rule bugs at plan time instead of as
cryptic executor KeyErrors.
"""

from __future__ import annotations

from typing import Set

from trino_tpu.expr.ir import RowExpression, SymbolRef
from trino_tpu.planner.nodes import (
    AggregationNode, FilterNode, GroupIdNode, JoinNode, OutputNode,
    PlanNode, ProjectNode, SemiJoinNode, SortNode, TableScanNode, TopNNode,
    UnnestNode, ValuesNode, WindowNode)


class PlanValidationError(Exception):
    pass


def _refs(e: RowExpression) -> Set[str]:
    out: Set[str] = set()

    def visit(x):
        if isinstance(x, SymbolRef):
            out.add(x.name)
        for c in x.children():
            visit(c)
    visit(e)
    return out


def validate_plan(root: PlanNode) -> PlanNode:
    """Raise PlanValidationError on a broken plan; returns the plan so it
    slots into the optimize() pipeline."""

    def check(node: PlanNode) -> None:
        for s in node.sources:
            check(s)
        child_syms: Set[str] = set()
        for s in node.sources:
            child_syms |= {x.name for x in s.outputs}

        def need(names: Set[str], what: str) -> None:
            missing = names - child_syms
            if missing:
                raise PlanValidationError(
                    f"{type(node).__name__}: {what} references "
                    f"{sorted(missing)} not produced by children")

        if isinstance(node, (TableScanNode, ValuesNode)):
            pass
        elif isinstance(node, FilterNode):
            need(_refs(node.predicate), "predicate")
        elif isinstance(node, ProjectNode):
            for _, e in node.assignments:
                need(_refs(e), "assignment")
        elif isinstance(node, JoinNode):
            left = {s.name for s in node.left.outputs}
            right = {s.name for s in node.right.outputs}
            for c in node.criteria:
                if c.left.name not in left:
                    raise PlanValidationError(
                        f"join criterion left {c.left.name} not in left "
                        "child")
                if c.right.name not in right:
                    raise PlanValidationError(
                        f"join criterion right {c.right.name} not in "
                        "right child")
            if node.filter is not None:
                need(_refs(node.filter), "residual filter")
            if node.output_symbols is not None:
                extra = {s.name for s in node.output_symbols} - (
                    left | right)
                if extra:
                    raise PlanValidationError(
                        f"join output_symbols {sorted(extra)} not in "
                        "either child")
        elif isinstance(node, SemiJoinNode):
            src = {s.name for s in node.source.outputs}
            filt = {s.name for s in node.filtering_source.outputs}
            for s in node.source_keys:
                if s.name not in src:
                    raise PlanValidationError(
                        f"semi-join source key {s.name} missing")
            for s in node.filtering_keys:
                if s.name not in filt:
                    raise PlanValidationError(
                        f"semi-join filtering key {s.name} missing")
        elif isinstance(node, AggregationNode):
            need({s.name for s in node.group_by}, "group keys")
            for _, call in node.aggregations:
                for a in call.args:
                    need(_refs(a), "aggregate argument")
        elif isinstance(node, (SortNode, TopNNode)):
            need({o.symbol.name for o in node.order_by}, "sort keys")
        elif isinstance(node, WindowNode):
            need({s.name for s in node.partition_by}, "partition keys")
            need({o.symbol.name for o in node.order_by}, "window order")
        elif isinstance(node, GroupIdNode):
            req = {s.name for gs in node.grouping_sets for s in gs}
            need(req, "grouping sets")
        elif isinstance(node, UnnestNode):
            need({s.name for s in node.arrays}, "unnest arrays")
        # outputs must be uniquely named
        names = [s.name for s in node.outputs]
        if len(names) != len(set(names)):
            dupes = sorted({n for n in names if names.count(n) > 1})
            raise PlanValidationError(
                f"{type(node).__name__}: duplicate output symbols "
                f"{dupes}")

    if isinstance(root, OutputNode):
        check(root.source)
        have = {s.name for s in root.source.outputs}
        missing = {s.name for s in root.symbols} - have
        if missing:
            raise PlanValidationError(
                f"Output references {sorted(missing)} not produced")
    else:
        check(root)
    return root
