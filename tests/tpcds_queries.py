"""Loader for the 99 TPC-DS benchmark queries.

The query TEXT is TPC-DS spec material (the reference ships it under
testing/trino-benchto-benchmarks .../tpcds/q*.sql with a
``${database}.${schema}.`` placeholder); we load it from the reference
checkout at runtime — nothing is copied into this repo — and strip the
placeholder. Tests skip when the reference tree isn't present.

Oracle variant: sqlite has no DATE type or INTERVAL arithmetic, so date
literals rewrite to epoch-day integers and ``(date +/- interval 'N' day)``
to integer addition (the same adaptation tests/tpch_sql.py documents).
"""

from __future__ import annotations

import datetime
import os
import re
from typing import Dict, Optional

QUERY_DIR = ("/root/reference/testing/trino-benchto-benchmarks/src/main/"
             "resources/sql/presto/tpcds")


def available() -> bool:
    return os.path.isdir(QUERY_DIR)


def load_queries() -> Dict[str, str]:
    out = {}
    for fn in sorted(os.listdir(QUERY_DIR)):
        m = re.match(r"q(\d+)\.sql$", fn)
        if not m:
            continue
        sql = open(os.path.join(QUERY_DIR, fn)).read()
        sql = sql.replace("${database}.${schema}.", "")
        out[f"q{int(m.group(1)):02d}"] = sql.strip().rstrip(";")
    return out


def _days(s: str) -> int:
    d = datetime.date.fromisoformat(s)
    return (d - datetime.date(1970, 1, 1)).days


def to_oracle_sql(sql: str) -> str:
    """Adapt engine SQL to the int-typed sqlite schema."""
    # (CAST('yyyy-mm-dd' AS DATE) +/- INTERVAL 'n' DAY) -> int arithmetic
    def cast_interval(m):
        base = _days(m.group(1))
        sign = 1 if m.group(2) == "+" else -1
        return str(base + sign * int(m.group(3)))
    sql = re.sub(
        r"\(?\s*CAST\s*\(\s*'(\d{4}-\d{2}-\d{2})'\s+AS\s+DATE\s*\)\s*"
        r"([+-])\s*INTERVAL\s+'(\d+)'\s+DAY\s*\)?",
        cast_interval, sql, flags=re.I)
    sql = re.sub(
        r"CAST\s*\(\s*'(\d{4}-\d{2}-\d{2})'\s+AS\s+DATE\s*\)",
        lambda m: str(_days(m.group(1))), sql, flags=re.I)
    sql = re.sub(r"DATE\s+'(\d{4}-\d{2}-\d{2})'",
                 lambda m: str(_days(m.group(1))), sql, flags=re.I)
    # leftover date +/- INTERVAL arithmetic on already-rewritten ints
    sql = re.sub(r"([+-])\s*INTERVAL\s+'(\d+)'\s+DAY",
                 lambda m: f"{m.group(1)} {m.group(2)}", sql, flags=re.I)
    # typed decimal literals: sqlite takes the bare numeric
    sql = re.sub(r"DECIMAL\s+'([0-9.+-]+)'", r"\1", sql, flags=re.I)
    # CAST(x AS DECIMAL(p,s)) -> REAL: sqlite's NUMERIC affinity keeps
    # integers integral and then divides integrally — the benchmark casts
    # exist precisely to force fractional division
    sql = re.sub(r"AS\s+DECIMAL\s*\(\s*\d+\s*,\s*\d+\s*\)", "AS REAL",
                 sql, flags=re.I)
    return sql
