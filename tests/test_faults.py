"""Fault-tolerant execution: injector mechanics, deadlines, cancellation,
graceful degradation.

Reference parity: testing/trino-faulttolerant-tests (fault injection +
RetryPolicy) + execution/QueryTracker time-limit enforcement +
QueryStateMachine cancellation. The oracle-verified chaos sweeps live in
tests/test_zz_chaos.py (named to sort after the seed suites so the
tier-1 wall budget spends on them last).
"""

import threading

import pytest

from trino_tpu.errors import (InjectedFault, QueryCanceledError,
                              QueryTimeoutError, is_retryable)
from trino_tpu.exec import LocalQueryRunner
from trino_tpu.exec.faults import SITES, FaultInjector
from trino_tpu.exec.memory import ExceededMemoryLimitError


# ------------------------------------------------------------- injector

def test_injector_deterministic():
    """Same seed -> same arm/fire decisions: chaos runs are replayable."""
    def run(seed):
        inj = FaultInjector(seed, 0.5)
        outcomes = []
        for task in range(40):
            inj.begin_task(task)
            try:
                for site in SITES:
                    inj.site(site)
                outcomes.append(None)
            except InjectedFault as e:
                assert is_retryable(e)
                outcomes.append(str(e))
        return outcomes
    assert run(7) == run(7)
    assert run(7) != run(8)
    fired = [o for o in run(7) if o is not None]
    assert fired       # rate 0.5 over 40 tasks must fire


def test_injector_rate_zero_disables():
    r = LocalQueryRunner.tpch("tiny")
    assert FaultInjector.from_session(r.session) is None


def test_injector_site_filter():
    inj = FaultInjector(1, 1.0, sites=("spill",))
    inj.begin_task("t")
    inj.site("fragment")          # not armed for this site: no raise
    with pytest.raises(InjectedFault):
        inj.site("spill")


# ------------------------------------------------------------- deadlines

def test_query_max_execution_time():
    r = LocalQueryRunner.tpch("tiny")
    r.session.set("query_max_execution_time", "1ms")
    with pytest.raises(QueryTimeoutError) as e:
        r.execute("SELECT count(*) FROM lineitem")
    assert e.value.error_name == "EXCEEDED_TIME_LIMIT"


def test_query_max_run_time():
    r = LocalQueryRunner.tpch("tiny")
    r.session.set("query_max_run_time", "1ms")
    with pytest.raises(QueryTimeoutError) as e:
        r.execute("SELECT count(*) FROM orders")
    assert e.value.error_name == "EXCEEDED_TIME_LIMIT"


def test_deadline_recorded_in_tracker():
    r = LocalQueryRunner.tpch("tiny")
    r.session.set("query_max_execution_time", "1ms")
    try:
        r.execute("SELECT count(*) FROM part")
    except QueryTimeoutError:
        pass
    r.session.properties.pop("query_max_execution_time")
    rows = r.execute(
        "SELECT error_name FROM system.runtime.queries "
        "WHERE state = 'FAILED' AND query LIKE '%FROM part%'").rows
    assert ("EXCEEDED_TIME_LIMIT",) in rows


def test_duration_parsing():
    from trino_tpu.exec.deadline import parse_duration
    assert parse_duration("") is None
    assert parse_duration(None) is None
    assert parse_duration(0) is None
    assert parse_duration("30s") == 30.0
    assert parse_duration("2m") == 120.0
    assert parse_duration("500ms") == 0.5
    assert parse_duration(2.5) == 2.5
    assert parse_duration("1h") == 3600.0


# ---------------------------------------------------------- cancellation

def test_pre_cancelled_event_stops_query():
    """A cancel that lands before execution starts aborts at the first
    cooperative checkpoint (the server's DELETE-while-QUEUED path)."""
    r = LocalQueryRunner.tpch("tiny")
    ev = threading.Event()
    ev.set()
    with pytest.raises(QueryCanceledError):
        r.execute("SELECT count(*) FROM lineitem", cancel_event=ev)


def test_cancel_current_mid_query():
    """A cancel from another thread stops a running query at a
    page-batch boundary and the tracker records CANCELED."""
    r = LocalQueryRunner.tpch("tiny")
    ev = threading.Event()
    errors = []

    def run():
        try:
            r.execute(
                "SELECT count(*) FROM lineitem l1, lineitem l2, "
                "lineitem l3 WHERE l1.l_orderkey = l2.l_orderkey "
                "AND l2.l_orderkey = l3.l_orderkey "
                "AND l1.l_partkey = l2.l_partkey",
                cancel_event=ev)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)
    th = threading.Thread(target=run)
    th.start()
    ev.set()                      # cancel immediately; checkpoints catch it
    th.join(timeout=120)
    assert not th.is_alive()
    assert errors and isinstance(errors[0], QueryCanceledError)
    rows = r.execute(
        "SELECT state FROM system.runtime.queries "
        "WHERE query LIKE '%l3.l_orderkey%' "
        "AND query NOT LIKE '%runtime%'").rows
    assert ("CANCELED",) in rows


# ------------------------------------------------- graceful degradation

def test_memory_degrade_retries_with_spill():
    """ExceededMemoryLimitError + an active retry policy: the fragment
    re-runs once with the spill path forced on and succeeds."""
    r = LocalQueryRunner.tpch("tiny")
    expected = r.execute(
        "SELECT c_custkey FROM customer ORDER BY c_acctbal, c_custkey").rows
    r.session.set("query_max_memory", 16384)
    r.session.set("retry_policy", "TASK")
    got = r.execute(
        "SELECT c_custkey FROM customer ORDER BY c_acctbal, c_custkey")
    assert got.rows == expected
    assert r.last_query_stats["retries"] >= 1
    # spill forcing must not leak into the session
    assert r.session.get("spill_enabled") is True
    assert int(r.session.get("sort_spill_threshold_bytes")) == 2 << 30


def test_memory_degrade_off_without_retry_policy():
    """retry_policy=NONE keeps the pre-FTE contract: over-limit fails."""
    r = LocalQueryRunner.tpch("tiny")
    r.session.set("query_max_memory", 16384)
    with pytest.raises(ExceededMemoryLimitError):
        r.execute("SELECT c_custkey FROM customer ORDER BY c_acctbal")
