"""Distributed execution over a TPU mesh.

Reference parity: Trino's data plane (execution/buffer/ + ExchangeClient +
PartitionedOutputOperator, SURVEY §2.8/§2.11) re-designed TPU-first: instead
of serialized pages pulled over HTTP, stages run as shard_map programs over a
jax.sharding.Mesh and REMOTE exchanges lower to ICI collectives —
  FIXED_HASH_DISTRIBUTION  -> radix bucketing + all_to_all
  FIXED_BROADCAST          -> all_gather
  SINGLE / gather          -> all_gather (+ shard-0 read)
"""

from trino_tpu.parallel.mesh import QueryMesh  # noqa: F401
from trino_tpu.parallel.exchange import (  # noqa: F401
    all_to_all_by_key, all_to_all_replicate, broadcast_page,
    detect_heavy_keys, gather_page)
