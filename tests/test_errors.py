"""Error taxonomy: every engine error carries a Trino-style error name.

Reference parity: core/trino-spi StandardErrorCode.java (name + code +
family) + TrinoException — the taxonomy is load-bearing: the retry
machinery keys on `retryable`, the HTTP protocol surfaces
errorName/errorCode/errorType, and the tracker records error_name.
"""

import pytest

from trino_tpu import errors as E
from trino_tpu.errors import (ExchangeTransportError, InjectedFault,
                              InvalidSessionPropertyError,
                              QueryCanceledError, QueryTimeoutError,
                              TrinoError, classify, is_retryable)
from trino_tpu.exec import LocalQueryRunner
from trino_tpu.exec.local_planner import ExecutionError
from trino_tpu.exec.memory import ExceededMemoryLimitError
from trino_tpu.sql.analyzer import SemanticError
from trino_tpu.sql.lexer import ParsingError


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch("tiny")


# ------------------------------------------------------------- structure

def test_code_families():
    assert E.GENERIC_USER_ERROR.code == 0
    assert E.GENERIC_INTERNAL_ERROR.code == 0x10000
    assert E.GENERIC_INSUFFICIENT_RESOURCES.code == 0x20000
    assert E.EXCEEDED_TIME_LIMIT.type == E.INSUFFICIENT_RESOURCES
    assert E.SYNTAX_ERROR.type == E.USER_ERROR
    assert E.REMOTE_TASK_ERROR.type == E.INTERNAL_ERROR


def test_retryable_taxonomy():
    """Only transient infrastructure failures retry; user/semantic/
    resource errors never do (the FTE retry predicate)."""
    assert is_retryable(InjectedFault("boom"))
    assert is_retryable(ExchangeTransportError("page lost"))
    assert not is_retryable(SemanticError("no such column"))
    assert not is_retryable(ParsingError("bad token"))
    assert not is_retryable(ExceededMemoryLimitError("over limit"))
    assert not is_retryable(QueryTimeoutError("too slow"))
    assert not is_retryable(QueryCanceledError("canceled"))
    assert not is_retryable(ExecutionError("operator bug"))
    assert not is_retryable(ValueError("random"))


def test_engine_errors_carry_names():
    """The satellite contract: every engine error class IS a TrinoError
    with a stable name, so nothing surfaces as a bare Python class."""
    cases = [
        (SemanticError("x"), "GENERIC_USER_ERROR", "USER_ERROR"),
        (ParsingError("x"), "SYNTAX_ERROR", "USER_ERROR"),
        (ExecutionError("x"), "GENERIC_INTERNAL_ERROR", "INTERNAL_ERROR"),
        (ExceededMemoryLimitError("x"), "EXCEEDED_LOCAL_MEMORY_LIMIT",
         "INSUFFICIENT_RESOURCES"),
        (QueryTimeoutError("x"), "EXCEEDED_TIME_LIMIT",
         "INSUFFICIENT_RESOURCES"),
        (QueryCanceledError("x"), "USER_CANCELED", "USER_ERROR"),
        (InjectedFault("x"), "REMOTE_TASK_ERROR", "INTERNAL_ERROR"),
        (InvalidSessionPropertyError("x"), "INVALID_SESSION_PROPERTY",
         "USER_ERROR"),
    ]
    for exc, name, family in cases:
        assert isinstance(exc, TrinoError)
        assert exc.error_name == name
        assert exc.error_type == family
        assert classify(exc).name == name


def test_classify_foreign_exceptions():
    assert classify(KeyError("unknown scalar function: f")).name == \
        "NOT_FOUND"
    assert classify(ZeroDivisionError()).name == "DIVISION_BY_ZERO"
    assert classify(RuntimeError("?")).name == "GENERIC_INTERNAL_ERROR"


# -------------------------------------------------- raised through engine

def test_parse_error_through_runner(runner):
    with pytest.raises(ParsingError) as e:
        runner.execute("SELEC 1")
    assert e.value.error_name == "SYNTAX_ERROR"


def test_semantic_error_through_runner(runner):
    with pytest.raises(SemanticError) as e:
        runner.execute("SELECT no_such_col FROM nation")
    assert e.value.error_name == "GENERIC_USER_ERROR"
    assert not e.value.retryable


def test_invalid_session_property_through_runner(runner):
    with pytest.raises(InvalidSessionPropertyError) as e:
        runner.execute("SET SESSION no_such_property = 'x'")
    assert e.value.error_name == "INVALID_SESSION_PROPERTY"
    # KeyError-compatible for pre-taxonomy callers
    assert isinstance(e.value, KeyError)
    assert "no_such_property" in str(e.value)


def test_tracker_records_error_name(runner):
    try:
        runner.execute("SELECT * FROM tpch.tiny.missing_table_for_err")
    except Exception:
        pass
    rows = runner.execute(
        "SELECT error_name FROM system.runtime.queries "
        "WHERE query LIKE '%missing_table_for_err%' "
        "AND state = 'FAILED'").rows
    assert rows and rows[0][0] == "GENERIC_USER_ERROR"
