"""Benchmark: TPC-H end-to-end wall-clock on the real chip.

Measurement ladder (BASELINE.md): #1 q6 tiny-smoke is folded into the SF1
run; #2 q1 SF1 (lineitem hash aggregation); #3 q3 **SF10** (3-way join
customer x orders x lineitem) — the actual ladder rung, not SF1. Every query
runs through the full engine (parse -> plan -> optimize -> execute). Prints
ONE JSON line; the headline metric stays q6 SF1 wall-clock, with the other
ladder rungs in "extra".

vs_baseline: the reference repo publishes no numbers (BASELINE.md); the
denominators are ballpark single-node Trino wall-clocks from its
LocalQueryRunner-style benchmarks on server CPUs — q6 SF1 ~1.0s, q1 SF1
~2.5s, q3 SF10 ~10s — so vs_baseline > 1 means faster than that estimate.

Data caveat (BASELINE.md north-star asks for bit-identical rows): the tpch
connector generates spec-shaped seeded data, not dbgen bitstreams, so the
comparison is same-shape wall-clock, not row-identical output.
"""

import json
import time

Q6 = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01'
  AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR
  AND l_discount BETWEEN 0.06 - 0.01 AND 0.06 + 0.01
  AND l_quantity < 24
"""

Q1 = """
SELECT l_returnflag, l_linestatus, sum(l_quantity) AS sum_qty,
       sum(l_extendedprice) AS sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
       avg(l_quantity) AS avg_qty, avg(l_extendedprice) AS avg_price,
       avg(l_discount) AS avg_disc, count(*) AS count_order
FROM lineitem
WHERE l_shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

Q3 = """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate LIMIT 10
"""

# ballpark single-node Java-engine estimates (no published numbers exist)
BASE_Q6_SF1_S = 1.0
BASE_Q1_SF1_S = 2.5
BASE_Q3_SF10_S = 10.0


def _time_query(runner, sql, iters=3):
    rows = runner.execute(sql).rows  # warm-up (compile) run, untimed
    assert rows, "query returned no rows"
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        runner.execute(sql)
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]  # median


def main():
    import trino_tpu
    # persistent compile cache: repeat driver rounds skip XLA recompiles
    trino_tpu.enable_persistent_cache()

    from trino_tpu.exec import LocalQueryRunner

    sf1 = LocalQueryRunner.tpch("sf1")
    q6 = _time_query(sf1, Q6)
    q1 = _time_query(sf1, Q1)
    sf10 = LocalQueryRunner.tpch("sf10")
    q3 = _time_query(sf10, Q3)
    print(json.dumps({
        "metric": "tpch_q6_sf1_wall_s",
        "value": round(q6, 4),
        "unit": "s",
        "vs_baseline": round(BASE_Q6_SF1_S / q6, 3),
        "extra": {
            "tpch_q1_sf1_wall_s": round(q1, 4),
            "tpch_q1_sf1_vs_baseline": round(BASE_Q1_SF1_S / q1, 3),
            "tpch_q3_sf10_wall_s": round(q3, 4),
            "tpch_q3_sf10_vs_baseline": round(BASE_Q3_SF10_S / q3, 3),
        },
    }))


if __name__ == "__main__":
    main()
