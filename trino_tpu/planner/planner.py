"""Logical planner: analyzed AST -> symbol-based plan.

Reference parity: sql/planner/LogicalPlanner.java:196 + QueryPlanner.java +
RelationPlanner.java + SubqueryPlanner.java. One-pass design: translation
types expressions while planning (analyzer rules live in sql/analyzer.py).

Subquery support (SubqueryPlanner + TransformCorrelated* rules condensed):
- uncorrelated scalar subquery  -> EnforceSingleRow + cross join
- correlated scalar aggregate with equality correlation
                                -> group-by-correlation-keys + LEFT join
- [NOT] IN (subquery)           -> SemiJoinNode (+ NOT via negated filter)
- [NOT] EXISTS with equality correlation -> SemiJoinNode on the keys
NOT IN is null-aware (SemiJoinNode.null_aware): the executor applies full
IN-subquery three-valued logic — a NULL probe value or a NULL in a non-empty
subquery result makes membership UNKNOWN, so NOT IN keeps a row only when
the subquery column is null-free (and x NOT IN (empty) is TRUE even for
NULL x). NOT EXISTS uses non-null-aware anti semantics (NULL correlation
keys simply never match).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from trino_tpu import types as T
from trino_tpu.expr.ir import (Call, Literal, RowExpression, SpecialForm,
                               SpecialKind, SymbolRef)
from trino_tpu.metadata import Metadata, Session
from trino_tpu.planner.nodes import (
    AggCall, AggregationNode, AggStep, AssignUniqueIdNode, DistinctLimitNode,
    EnforceSingleRowNode, FilterNode, GroupIdNode, JoinClause, JoinKind,
    JoinNode, LimitNode, OffsetNode, Ordering, OutputNode, PlanNode,
    ProjectNode, SemiJoinNode, SortNode, Symbol, SymbolAllocator,
    TableScanNode, TopNNode, UnionNode, UnnestNode, ValuesNode,
    WindowFunction, WindowNode)
from trino_tpu.planner.translate import (
    ExpressionTranslator, Field, Scope, cast_to, make_comparison)
from trino_tpu.sql import tree as t
from trino_tpu.sql.analyzer import (SemanticError, common_type, is_aggregate,
                                    is_window, resolve_aggregate)


@dataclasses.dataclass
class RelationPlan:
    node: PlanNode
    scope: Scope


def _conjuncts(e: t.Expression) -> List[t.Expression]:
    if isinstance(e, t.LogicalBinary) and e.op == "AND":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _combine_ast(parts: Sequence[t.Expression]) -> t.Expression:
    out = parts[0]
    for p in parts[1:]:
        out = t.LogicalBinary("AND", out, p)
    return out


def _has_subquery(e: t.Expression) -> bool:
    for n in t.walk(e):
        if isinstance(n, (t.SubqueryExpression, t.ExistsPredicate,
                          t.InPredicate)):
            if isinstance(n, t.InPredicate) and not isinstance(
                    n.value_list, t.SubqueryExpression):
                continue
            return True
    return False


def combine_conjuncts(parts: Sequence[RowExpression]) -> RowExpression:
    out = parts[0]
    for p in parts[1:]:
        out = SpecialForm(SpecialKind.AND, (out, p), T.BOOLEAN)
    return out


class LogicalPlanner:
    """LogicalPlanner.java:196 — entry point producing an OutputNode root."""

    def __init__(self, metadata: Metadata, session: Session):
        self.metadata = metadata
        self.session = session
        self.symbols = SymbolAllocator()

    def plan(self, statement: t.Statement) -> OutputNode:
        if isinstance(statement, t.Query):
            plan, names = self._plan_root_query(statement)
            return OutputNode(plan.node, tuple(names),
                              tuple(f.symbol for f in plan.scope.fields))
        raise SemanticError(
            f"cannot plan statement: {type(statement).__name__}")

    # ------------------------------------------------------------- queries

    def _plan_root_query(self, query: t.Query):
        plan = self._plan_query(query, None, {})
        names = []
        for i, f in enumerate(plan.scope.fields):
            names.append(f.name or f"_col{i}")
        return plan, names

    def _plan_query(self, query: t.Query, outer: Optional[Scope],
                    ctes: Dict[str, t.WithQuery]) -> RelationPlan:
        ctes = dict(ctes)
        if query.with_ is not None:
            if query.with_.recursive:
                raise SemanticError("recursive WITH not supported")
            for wq in query.with_.queries:
                ctes[wq.name.value] = wq
        plan = self._plan_query_body(query.body, outer, ctes)
        # trailing ORDER BY / OFFSET / LIMIT of a query expression
        plan = self._plan_order_limit(plan, query.order_by, query.offset,
                                      query.limit, outer, ctes)
        return plan

    def _plan_query_body(self, body: t.QueryBody, outer: Optional[Scope],
                         ctes: Dict[str, t.WithQuery]) -> RelationPlan:
        if isinstance(body, t.QuerySpecification):
            return self._plan_query_spec(body, outer, ctes)
        if isinstance(body, t.SetOperation):
            return self._plan_set_operation(body, outer, ctes)
        raise SemanticError(f"unsupported query body: {type(body).__name__}")

    def _plan_set_operation(self, body: t.SetOperation, outer, ctes
                            ) -> RelationPlan:
        if body.op in ("INTERSECT", "EXCEPT"):
            return self._plan_intersect_except(body, outer, ctes)
        if body.op != "UNION":
            raise SemanticError(f"{body.op} not supported yet")
        left = self._plan_query_body(body.left, outer, ctes)
        right = self._plan_query_body(body.right, outer, ctes)
        lf, rf = left.scope.fields, right.scope.fields
        if len(lf) != len(rf):
            raise SemanticError("UNION inputs have different column counts")
        out_syms, mappings, children = [], [], []
        casted = []
        for side in (left, right):
            casted.append(side)
        # compute common types; insert cast projections where needed
        types = []
        for a, b in zip(lf, rf):
            ct = common_type(a.symbol.type, b.symbol.type)
            if ct is None:
                raise SemanticError("UNION column types incompatible")
            types.append(ct)
        sides = []
        for side in (left, right):
            needs_cast = any(f.symbol.type != ty
                             for f, ty in zip(side.scope.fields, types))
            if needs_cast:
                assigns = []
                for f, ty in zip(side.scope.fields, types):
                    sym = self.symbols.new(f.name or "col", ty)
                    assigns.append((sym, cast_to(f.symbol.ref(), ty)))
                node = ProjectNode(side.node, tuple(assigns))
                sides.append((node, [s for s, _ in assigns]))
            else:
                sides.append((side.node, [f.symbol
                                          for f in side.scope.fields]))
        for i, (f, ty) in enumerate(zip(lf, types)):
            out_syms.append(self.symbols.new(f.name or f"col{i}", ty))
        mappings = tuple(
            tuple(side_syms[i] for _, side_syms in sides)
            for i in range(len(out_syms)))
        children = tuple(node for node, _ in sides)
        union = UnionNode(children, tuple(out_syms), mappings)
        fields = [Field(f.name, None, s) for f, s in zip(lf, out_syms)]
        result: PlanNode = union
        if body.distinct:
            result = AggregationNode(union, tuple(out_syms), ())
        return RelationPlan(result, Scope(fields, outer))

    def _plan_intersect_except(self, body: t.SetOperation, outer, ctes
                               ) -> RelationPlan:
        """INTERSECT/EXCEPT [DISTINCT] as distinct(left) SEMI/ANTI-joined
        against the right on every column (sql/planner/QueryPlanner's
        set-operation lowering via SemiJoin + MarkDistinct, condensed).
        NULL rows never match (generated datasets are null-free here;
        IS-NOT-DISTINCT matching is a known deviation for NULL keys)."""
        if not body.distinct:
            raise SemanticError(f"{body.op} ALL not supported yet")
        left = self._plan_query_body(body.left, outer, ctes)
        right = self._plan_query_body(body.right, outer, ctes)
        lf, rf = left.scope.fields, right.scope.fields
        if len(lf) != len(rf):
            raise SemanticError(
                f"{body.op} inputs have different column counts")
        types = []
        for a, b in zip(lf, rf):
            ct = common_type(a.symbol.type, b.symbol.type)
            if ct is None:
                raise SemanticError(f"{body.op} column types incompatible")
            types.append(ct)

        def casted(side):
            if all(f.symbol.type == ty
                   for f, ty in zip(side.scope.fields, types)):
                return side.node, [f.symbol for f in side.scope.fields]
            assigns = []
            for f, ty in zip(side.scope.fields, types):
                sym = self.symbols.new(f.name or "col", ty)
                assigns.append((sym, cast_to(f.symbol.ref(), ty)))
            return ProjectNode(side.node, tuple(assigns)), \
                [s for s, _ in assigns]
        lnode, lsyms = casted(left)
        rnode, rsyms = casted(right)
        distinct = AggregationNode(lnode, tuple(lsyms), ())
        match = self.symbols.new("setopmatch", T.BOOLEAN)
        semi = SemiJoinNode(distinct, rnode, tuple(lsyms), tuple(rsyms),
                            match, negate=False, null_aware=False)
        keep = match.ref() if body.op == "INTERSECT" else SpecialForm(
            SpecialKind.NOT, (match.ref(),), T.BOOLEAN)
        filt = FilterNode(semi, keep)
        proj = ProjectNode(filt, tuple((s, s.ref()) for s in lsyms))
        fields = [Field(f.name, None, s) for f, s in zip(lf, lsyms)]
        return RelationPlan(proj, Scope(fields, outer))

    # ----------------------------------------------------------- relations

    def _plan_relation(self, rel: t.Relation, outer: Optional[Scope],
                       ctes: Dict[str, t.WithQuery]) -> RelationPlan:
        if isinstance(rel, t.Table):
            name = rel.name
            if len(name.parts) == 1 and name.parts[0] in ctes:
                wq = ctes[name.parts[0]]
                sub = self._plan_query(wq.query, outer,
                                       {k: v for k, v in ctes.items()
                                        if k != name.parts[0]})
                alias = wq.name.value
                fields = []
                for i, f in enumerate(sub.scope.fields):
                    col = (wq.column_names[i].value
                           if i < len(wq.column_names) else f.name)
                    fields.append(Field(col, alias, f.symbol))
                return RelationPlan(sub.node, Scope(fields, outer))
            return self._plan_table(rel, outer)
        if isinstance(rel, t.AliasedRelation):
            sub = self._plan_relation(rel.relation, outer, ctes)
            alias = rel.alias.value
            fields = []
            for i, f in enumerate(sub.scope.fields):
                col = (rel.column_names[i].value
                       if i < len(rel.column_names) else f.name)
                fields.append(Field(col, alias, f.symbol))
            return RelationPlan(sub.node, Scope(fields, outer))
        if isinstance(rel, t.TableSubquery):
            # CTEs stay visible inside derived tables (q33-style
            # `FROM (SELECT ... FROM some_cte UNION ALL ...)`)
            sub = self._plan_query(rel.query, outer, ctes)
            # subquery loses outer qualifiers
            fields = [Field(f.name, None, f.symbol)
                      for f in sub.scope.fields]
            return RelationPlan(sub.node, Scope(fields, outer))
        if isinstance(rel, t.Join):
            return self._plan_join(rel, outer, ctes)
        if isinstance(rel, t.Values):
            return self._plan_values(rel, outer)
        if isinstance(rel, t.Unnest):
            # standalone FROM UNNEST(...): expand against one dummy row
            dummy = self.symbols.new("unnest_src", T.BIGINT)
            src = RelationPlan(
                ValuesNode((dummy,), ((Literal(0, T.BIGINT),),)),
                Scope([], outer))
            return self._plan_unnest(src, rel, None, outer)
        raise SemanticError(f"unsupported relation: {type(rel).__name__}")

    def _plan_unnest(self, left: RelationPlan, un: t.Unnest,
                     alias_rel: Optional[t.AliasedRelation],
                     outer) -> RelationPlan:
        """CROSS JOIN UNNEST(arr [, ...]) [WITH ORDINALITY] [AS a(c...)].
        One ARRAY argument yields one element column; a MAP argument
        yields (key, value)."""
        tr = ExpressionTranslator(left.scope)
        exprs = [tr.translate(e) for e in un.expressions]
        if len(exprs) > 1:
            raise SemanticError(
                "UNNEST of multiple arrays (zip) not supported yet")
        node = left.node
        array_syms = []
        pre = [(s, s.ref()) for s in node.outputs]
        for e in exprs:
            if not isinstance(e.type, (T.ArrayType, T.MapType)):
                raise SemanticError(
                    f"UNNEST argument must be ARRAY or MAP, got "
                    f"{e.type.display()}")
            if isinstance(e, SymbolRef):
                array_syms.append(Symbol(e.name, e.type))
            else:
                sym = self.symbols.new("unnest_arr", e.type)
                pre.append((sym, e))
                array_syms.append(sym)
        if len(pre) > len(node.outputs):
            node = ProjectNode(node, tuple(pre))
        names = [c.value for c in alias_rel.column_names] \
            if alias_rel is not None else []
        alias = alias_rel.alias.value if alias_rel is not None else None
        elements = []
        fields = list(left.scope.fields)
        ni = 0

        def next_name(default):
            nonlocal ni
            name = names[ni] if ni < len(names) else default
            ni += 1
            return name

        for s in array_syms:
            if isinstance(s.type, T.MapType):
                k = self.symbols.new("unnest_key", s.type.key)
                v = self.symbols.new("unnest_val", s.type.value)
                elements.append((k, v))
                fields.append(Field(next_name("key"), alias, k))
                fields.append(Field(next_name("value"), alias, v))
            else:
                el = self.symbols.new("unnest_el", s.type.element)
                elements.append((el,))
                fields.append(Field(next_name("col"), alias, el))
        ordi = None
        if un.with_ordinality:
            ordi = self.symbols.new("ordinality", T.BIGINT)
            fields.append(Field(next_name("ordinality"), alias, ordi))
        out = UnnestNode(node, tuple(array_syms), tuple(elements), ordi)
        return RelationPlan(out, Scope(fields, outer))

    def _plan_table(self, rel: t.Table, outer: Optional[Scope]) -> RelationPlan:
        qname = self.metadata.resolve_table_name(rel.name.parts, self.session)
        handle = self.metadata.get_table_handle(qname)
        if handle is None:
            raise SemanticError(f"table not found: {qname}")
        handle = self._pin_snapshot(rel, qname, handle)
        meta = self.metadata.get_table_metadata(qname.catalog, handle)
        columns = self.metadata.get_column_handles(qname.catalog, handle)
        assignments = []
        fields = []
        for col in columns:
            sym = self.symbols.new(col.name, col.type)
            assignments.append((sym, col))
            fields.append(Field(col.name, qname.table, sym))
        node = TableScanNode(qname.catalog, handle, tuple(assignments))
        return RelationPlan(node, Scope(fields, outer))

    def _pin_snapshot(self, rel: t.Table, qname, handle):
        """Resolve time travel (`FOR VERSION|TIMESTAMP AS OF`) and the
        MV refresher's internal scan pins into a version-pinned handle.
        Scan pins ride the session as `_mv_scan_pins`:
        {(catalog, schema, table): (v_from_or_None, v_to)} — never set
        by SQL; the runner bypasses the plan/result caches while they
        are armed."""
        pins = getattr(self.session, "_mv_scan_pins", None) or {}
        pin = pins.get((qname.catalog, qname.schema, qname.table))
        if rel.version is None and rel.timestamp is None and pin is None:
            return handle
        conn = self.metadata.connector(qname.catalog)
        resolve = getattr(conn.metadata, "resolve_version", None)
        if resolve is None:
            raise SemanticError(
                f"catalog '{qname.catalog}' does not support versioned "
                f"(time travel) reads")
        if pin is not None:
            delta_from, v_to = pin
            return dataclasses.replace(handle, version=int(v_to),
                                       delta_from=delta_from)
        try:
            if rel.version is not None:
                v = resolve(qname.schema_table,
                            version=_literal_version(rel.version))
            else:
                v = resolve(qname.schema_table,
                            timestamp=_literal_timestamp(rel.timestamp))
        except KeyError as e:
            raise SemanticError(str(e))
        return dataclasses.replace(handle, version=v)

    def _plan_values(self, rel: t.Values, outer) -> RelationPlan:
        rows = []
        for row_expr in rel.rows:
            items = (row_expr.items if isinstance(row_expr, t.Row)
                     else (row_expr,))
            tr = ExpressionTranslator(Scope([], None), session=self.session)
            rows.append(tuple(tr.translate(e) for e in items))
        width = len(rows[0])
        if any(len(r) != width for r in rows):
            raise SemanticError("VALUES rows have different column counts")
        types = []
        for i in range(width):
            ct = rows[0][i].type
            for r in rows[1:]:
                nt = common_type(ct, r[i].type)
                if nt is None:
                    raise SemanticError("VALUES column types incompatible")
                ct = nt
        # degrade unknown (all-null column) to bigint for execution
            types.append(T.BIGINT if isinstance(ct, T.UnknownType) else ct)
        rows = [tuple(cast_to(e, types[i]) for i, e in enumerate(r))
                for r in rows]
        syms = tuple(self.symbols.new(f"_col{i}", types[i])
                     for i in range(width))
        fields = [Field(f"_col{i}", None, s) for i, s in enumerate(syms)]
        return RelationPlan(ValuesNode(syms, tuple(rows)),
                            Scope(fields, outer))

    def _plan_join(self, rel: t.Join, outer, ctes) -> RelationPlan:
        # UNNEST on the right side is LATERAL-correlated: its expressions
        # see the LEFT relation (RelationPlanner.planJoinUnnest analog)
        inner_right = rel.right
        unnest_alias = None
        if isinstance(inner_right, t.AliasedRelation) and \
                isinstance(inner_right.relation, t.Unnest):
            unnest_alias = inner_right
            inner_right = inner_right.relation
        if isinstance(inner_right, t.Unnest):
            if rel.join_type not in ("IMPLICIT", "CROSS", "INNER"):
                raise SemanticError(
                    f"{rel.join_type} JOIN UNNEST not supported")
            left = self._plan_relation(rel.left, outer, ctes)
            return self._plan_unnest(left, inner_right, unnest_alias,
                                     outer)
        left = self._plan_relation(rel.left, outer, ctes)
        right = self._plan_relation(rel.right, outer, ctes)
        join_scope = Scope(left.scope.fields + right.scope.fields, outer)

        if rel.join_type in ("IMPLICIT", "CROSS"):
            node = JoinNode(JoinKind.CROSS, left.node, right.node, ())
            return RelationPlan(node, join_scope)

        kind = {"INNER": JoinKind.INNER, "LEFT": JoinKind.LEFT,
                "RIGHT": JoinKind.RIGHT, "FULL": JoinKind.FULL}[rel.join_type]
        swapped = False
        if kind == JoinKind.RIGHT:
            # normalize RIGHT to LEFT by swapping inputs (Trino AstBuilder
            # keeps RIGHT; its LocalExecutionPlanner flips — we flip early).
            # join_scope above was built pre-swap, preserving SELECT * order;
            # the USING branch below rebuilds fields orientation-aware.
            left, right = right, left
            kind = JoinKind.LEFT
            swapped = True

        criteria: List[JoinClause] = []
        residual: List[RowExpression] = []
        using_cols: List[str] = []
        if isinstance(rel.criteria, t.JoinUsing) or rel.criteria is None:
            names = ([c.value for c in rel.criteria.columns]
                     if rel.criteria else
                     sorted({f.name for f in left.scope.fields} &
                            {f.name for f in right.scope.fields}))
            for name in names:
                lf = [f for f in left.scope.fields if f.name == name]
                rf = [f for f in right.scope.fields if f.name == name]
                if len(lf) != 1 or len(rf) != 1:
                    raise SemanticError(f"USING column {name} ambiguous")
                lsym, rsym = lf[0].symbol, rf[0].symbol
                lx, rx = self._coerce_join_keys(lsym.ref(), rsym.ref())
                lsym2 = self._key_symbol(lx, "join_l")
                rsym2 = self._key_symbol(rx, "join_r")
                if lsym2 != lsym or rsym2 != rsym:
                    # needs projection below each side
                    left, lsym2 = self._append_projection(left, lx)
                    right, rsym2 = self._append_projection(right, rx)
                criteria.append(JoinClause(lsym2, rsym2))
                using_cols.append(name)
            # USING scope: join columns once, then remaining columns of the
            # ORIGINAL left, then remaining right (Trino output order; the
            # key value comes from the preserved/probe side = post-swap left)
            key_fields = [f for f in left.scope.fields
                          if f.name in using_cols]
            first, second = (right, left) if swapped else (left, right)
            fields = (key_fields +
                      [f for f in first.scope.fields
                       if f.name not in using_cols] +
                      [f for f in second.scope.fields
                       if f.name not in using_cols])
            join_scope = Scope(fields, outer)
        elif isinstance(rel.criteria, t.JoinOn):
            criteria, residual, left, right = self._extract_equi_criteria(
                rel.criteria.expression, left, right, join_scope)
        if kind == JoinKind.LEFT and residual:
            # ON conditions over the build side only restrict which build
            # rows can match -> pre-filter the build side (outer semantics
            # preserved). Mixed-side non-equi LEFT conditions need operator
            # filter support (tracked; q21-class queries).
            right_syms = {f.symbol.name for f in right.scope.fields}
            kept = []
            for p in residual:
                syms = _symbols_in(p)
                if syms and syms <= right_syms:
                    right = RelationPlan(FilterNode(right.node, p),
                                         right.scope)
                else:
                    kept.append(p)
            residual = kept
            if residual:
                raise SemanticError(
                    "LEFT JOIN with non-equi conditions across both sides "
                    "is not supported yet")
        node = JoinNode(kind, left.node, right.node, tuple(criteria),
                        combine_conjuncts(residual) if residual else None)
        return RelationPlan(node, Scope(join_scope.fields, outer))

    def _append_projection(self, plan: RelationPlan, expr: RowExpression
                           ) -> Tuple[RelationPlan, Symbol]:
        if isinstance(expr, SymbolRef):
            return plan, Symbol(expr.name, expr.type)
        sym = self.symbols.new("expr", expr.type)
        assigns = [(f.symbol, f.symbol.ref()) for f in plan.scope.fields]
        assigns.append((sym, expr))
        node = ProjectNode(plan.node, tuple(assigns))
        return RelationPlan(node, plan.scope), sym

    def _key_symbol(self, expr: RowExpression, hint: str) -> Symbol:
        if isinstance(expr, SymbolRef):
            return Symbol(expr.name, expr.type)
        return self.symbols.new(hint, expr.type)

    def _coerce_join_keys(self, lx: RowExpression, rx: RowExpression):
        ct = common_type(lx.type, rx.type)
        if ct is None:
            raise SemanticError("join key types incompatible")
        return cast_to(lx, ct), cast_to(rx, ct)

    def _extract_equi_criteria(self, on: t.Expression, left: RelationPlan,
                               right: RelationPlan, join_scope: Scope):
        """Split ON into equi-join clauses + residual filter
        (ReorderJoins/JoinNode criteria extraction)."""
        left_names = {f.symbol.name for f in left.scope.fields}
        right_names = {f.symbol.name for f in right.scope.fields}
        criteria: List[JoinClause] = []
        residual: List[RowExpression] = []
        tr = ExpressionTranslator(join_scope, session=self.session)
        for conj in _conjuncts(on):
            handled = False
            if isinstance(conj, t.ComparisonExpression) and conj.op == "=":
                a = tr.translate(conj.left)
                b = tr.translate(conj.right)
                sa = _symbols_in(a)
                sb = _symbols_in(b)
                if sa <= left_names and sb <= right_names and sa and sb:
                    la, rb = a, b
                elif sb <= left_names and sa <= right_names and sa and sb:
                    la, rb = b, a
                else:
                    la = rb = None
                if la is not None:
                    la, rb = self._coerce_join_keys(la, rb)
                    lsym = self._key_symbol(la, "join_l")
                    rsym = self._key_symbol(rb, "join_r")
                    if not isinstance(la, SymbolRef):
                        left, lsym = self._append_projection(left, la)
                    if not isinstance(rb, SymbolRef):
                        right, rsym = self._append_projection(right, rb)
                    criteria.append(JoinClause(lsym, rsym))
                    handled = True
            if not handled:
                residual.append(tr.translate(conj))
        return criteria, residual, left, right

    # ------------------------------------------------- query specification

    def _plan_query_spec(self, spec: t.QuerySpecification,
                         outer: Optional[Scope],
                         ctes: Dict[str, t.WithQuery]) -> RelationPlan:
        # FROM
        if spec.from_ is not None:
            source = self._plan_relation(spec.from_, outer, ctes)
        else:
            sym = self.symbols.new("dual", T.BIGINT)
            source = RelationPlan(
                ValuesNode((sym,), ((Literal(0, T.BIGINT),),)),
                Scope([], outer))
        builder = _PlanBuilder(self, source, ctes)

        # WHERE
        if spec.where is not None:
            builder.plan_where(spec.where)

        # aggregation / grouping
        select_items = self._expand_select(spec, builder.scope())
        agg_calls = self._collect_aggregates(spec, select_items)
        group_elements = spec.group_by.elements if spec.group_by else ()
        has_agg = bool(agg_calls) or spec.group_by is not None
        if has_agg:
            builder.plan_aggregation(group_elements, agg_calls, select_items,
                                     spec.having)
        if spec.having is not None:
            builder.plan_having(spec.having)

        # window functions
        win_calls = [fc for fc in _find_calls(
            [e for e, _ in select_items] +
            [s.key for s in (spec.order_by or ())])
            if fc.window is not None]
        if win_calls:
            builder.plan_windows(win_calls)

        # SELECT projection (+ extra sort keys), DISTINCT, ORDER BY, LIMIT.
        # ORDER BY may reference source columns that are not selected
        # (QueryPlanner's ORDER BY scope): carry them through the projection
        # and prune after the sort.
        order_keep: Tuple[Symbol, ...] = ()
        pre_fields = builder.scope().fields
        if spec.order_by and not spec.select.distinct:
            order_keep = builder.sort_key_source_symbols(spec.order_by)
        out_fields = builder.plan_select(select_items, keep=order_keep)
        if spec.select.distinct:
            builder.plan_distinct(out_fields)
        if spec.order_by:
            builder.plan_order_by(spec.order_by, out_fields,
                                  pre_fields if order_keep else None)
        if spec.offset is not None:
            builder.plan_offset(_literal_count(spec.offset, "OFFSET"))
        if spec.limit is not None:
            builder.plan_limit(_literal_count(spec.limit, "LIMIT"))
        builder.prune_to(out_fields)
        return RelationPlan(builder.node, Scope(out_fields, outer))

    def _plan_order_limit(self, plan: RelationPlan,
                          order_by: Tuple[t.SortItem, ...],
                          offset: Optional[t.Expression],
                          limit: Optional[t.Expression],
                          outer, ctes) -> RelationPlan:
        if not order_by and offset is None and limit is None:
            return plan
        builder = _PlanBuilder(self, plan, ctes)
        fields = plan.scope.fields
        if order_by:
            builder.plan_order_by(order_by, fields)
        if offset is not None:
            builder.plan_offset(_literal_count(offset, "OFFSET"))
        if limit is not None:
            builder.plan_limit(_literal_count(limit, "LIMIT"))
        return RelationPlan(builder.node, Scope(fields, outer))

    # ------------------------------------------------------------ helpers

    def _expand_select(self, spec: t.QuerySpecification, scope: Scope
                       ) -> List[Tuple[t.Expression, Optional[str]]]:
        """Select items -> (expression AST, output name); expands `*`."""
        items: List[Tuple[t.Expression, Optional[str]]] = []
        for item in spec.select.items:
            if isinstance(item, t.AllColumns):
                prefix = item.prefix.parts[-1] if item.prefix else None
                matched = False
                for f in scope.fields:
                    if prefix is None or f.qualifier == prefix:
                        if f.name is None:
                            continue
                        matched = True
                        items.append((t.Identifier(f.name) if prefix is None
                                      else t.DereferenceExpression(
                                          t.Identifier(prefix),
                                          t.Identifier(f.name)), f.name))
                if not matched:
                    raise SemanticError(
                        f"no columns for {prefix}.*" if prefix else
                        "SELECT * with no FROM columns")
            else:
                assert isinstance(item, t.SingleColumn)
                name = None
                if item.alias is not None:
                    name = item.alias.value
                elif isinstance(item.expression, t.Identifier):
                    name = item.expression.value
                elif isinstance(item.expression, t.DereferenceExpression):
                    name = item.expression.field.value
                items.append((item.expression, name))
        return items

    def _collect_aggregates(self, spec, select_items):
        exprs = [e for e, _ in select_items]
        if spec.having is not None:
            exprs.append(spec.having)
        for s in (spec.order_by or ()):
            exprs.append(s.key)
        return [fc for fc in _find_calls(exprs)
                if is_aggregate(fc.name.suffix) and fc.window is None]


def _find_calls(exprs: Sequence[t.Expression]) -> List[t.FunctionCall]:
    """Top-most aggregate/window FunctionCalls (not nested inside another)."""
    out: List[t.FunctionCall] = []
    seen = set()

    def visit(node: t.Expression):
        if isinstance(node, t.FunctionCall) and (
                is_aggregate(node.name.suffix) or node.window is not None):
            if id(node) not in seen:
                seen.add(id(node))
                out.append(node)
            if node.window is not None:
                # a window call may legally contain GROUP aggregates —
                # sum(sum(x)) OVER (...), rank() OVER (ORDER BY sum(x)) —
                # which must be collected for the aggregation phase
                for a in node.args:
                    visit(a)
                for e in node.window.partition_by:
                    visit(e)
                for s in node.window.order_by:
                    visit(s.key)
            return  # below a plain aggregate: nested aggs are illegal
        if isinstance(node, (t.SubqueryExpression, t.ExistsPredicate)):
            return  # subquery aggregates belong to the subquery
        for child in _ast_children(node):
            visit(child)

    for e in exprs:
        visit(e)
    return out


def _ast_children(node: t.Node):
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        items = v if isinstance(v, tuple) else (v,)
        for item in items:
            if isinstance(item, t.Node):
                yield item


def _symbols_in(e: RowExpression) -> set:
    out = set()

    def visit(x: RowExpression):
        if isinstance(x, SymbolRef):
            out.add(x.name)
        for c in x.children():
            visit(c)
    visit(e)
    return out


def _literal_count(e: t.Expression, what: str) -> int:
    if isinstance(e, t.LongLiteral):
        return e.value
    raise SemanticError(f"{what} must be a literal integer")


def _literal_version(e: t.Expression) -> int:
    if isinstance(e, t.LongLiteral):
        return int(e.value)
    raise SemanticError(
        "FOR VERSION AS OF expects a literal integer manifest version")


def _literal_timestamp(e: t.Expression) -> float:
    """FOR TIMESTAMP AS OF resolution: literal timestamp/string ->
    epoch seconds (manifest `committed_at` scale)."""
    from trino_tpu.planner.translate import _parse_timestamp
    if isinstance(e, (t.TimestampLiteral, t.StringLiteral)):
        text = e.text if isinstance(e, t.TimestampLiteral) else e.value
        try:
            return _parse_timestamp(text) / 1e6
        except ValueError as err:
            raise SemanticError(f"invalid timestamp: {text!r}") from err
    if isinstance(e, (t.LongLiteral, t.DoubleLiteral)):
        return float(e.value)  # epoch seconds
    if isinstance(e, t.DecimalLiteral):
        return float(e.text)   # epoch seconds with a fractional part
    raise SemanticError(
        "FOR TIMESTAMP AS OF expects a literal timestamp")


class _PlanBuilder:
    """QueryPlanner's running (plan, translations) state."""

    def __init__(self, planner: LogicalPlanner, relation: RelationPlan,
                 ctes: Dict[str, t.WithQuery]):
        self.planner = planner
        self.node = relation.node
        self._scope = relation.scope
        self.ctes = ctes
        self.substitutions: Dict[RowExpression, Symbol] = {}
        self._grouping_info = None

    def scope(self) -> Scope:
        return self._scope

    def translator(self) -> ExpressionTranslator:
        return ExpressionTranslator(
            self._scope, self.substitutions,
            subquery_handler=self._handle_subquery,
            session=self.planner.session,
            grouping_handler=(self._grouping_expr
                              if self._grouping_info else None))

    def _grouping_expr(self, tr, node):
        """grouping(k1, ..., kn) -> SWITCH over the GroupId symbol: bit i
        (MSB-first) set when key i is aggregated away in the row's
        grouping set (GroupingOperationRewriter.java semantics)."""
        gid, sets_names = self._grouping_info
        arg_names = []
        for a in node.args:
            e = tr._translate(a)
            if not isinstance(e, SymbolRef):
                raise SemanticError(
                    "grouping() arguments must be grouping keys")
            arg_names.append(e.name)
        switch: List[RowExpression] = []
        for i, present in enumerate(sets_names):
            mask = 0
            for j, an in enumerate(arg_names):
                if an not in present:
                    mask |= 1 << (len(arg_names) - 1 - j)
            switch.append(Call("eq", (gid.ref(), Literal(i, T.BIGINT)),
                               T.BOOLEAN))
            switch.append(Literal(mask, T.BIGINT))
        switch.append(Literal(0, T.BIGINT))
        return SpecialForm(SpecialKind.SWITCH, tuple(switch), T.BIGINT)

    # -------------------------------------------------------- WHERE/HAVING

    def plan_where(self, where: t.Expression):
        # apply subquery-free conjuncts FIRST: subquery translation captures
        # self.node as the probe/outer side (SubqueryPlanner's contract), so
        # base filters must already be in place or the decorrelated plan
        # re-executes the unfiltered (possibly cross-join) outer subtree
        plain: List[t.Expression] = []
        with_sub: List[t.Expression] = []
        for conj in _conjuncts(where):
            (with_sub if _has_subquery(conj) else plain).append(conj)
        if plain and with_sub:
            pred = self.translator().translate(_combine_ast(plain))
            if not isinstance(pred.type, T.BooleanType):
                raise SemanticError("WHERE clause must be boolean")
            self.node = FilterNode(self.node, pred)
            where = _combine_ast(with_sub)
        pred = self.translator().translate(where)
        if not isinstance(pred.type, T.BooleanType):
            raise SemanticError("WHERE clause must be boolean")
        self.node = FilterNode(self.node, pred)

    def plan_having(self, having: t.Expression):
        pred = self.translator().translate(having)
        self.node = FilterNode(self.node, pred)

    # --------------------------------------------------------- aggregation

    def plan_aggregation(self, group_elements, agg_calls, select_items,
                         having):
        planner = self.planner
        tr = self.translator()
        # translate grouping expressions (flat list for simple GROUP BY;
        # grouping-set structure preserved for GroupId lowering)
        grouping_sets: List[List[RowExpression]] = []
        flat: List[RowExpression] = []
        simple = True
        for el in group_elements:
            if isinstance(el, t.SimpleGroupBy):
                for e in el.expressions:
                    flat.append(self._group_expr(tr, e, select_items))
            elif isinstance(el, t.Rollup):
                simple = False
                exprs = [self._group_expr(tr, e, select_items)
                         for e in el.expressions]
                grouping_sets = [exprs[:i] for i in range(len(exprs), -1, -1)]
                flat.extend(exprs)
            elif isinstance(el, t.Cube):
                simple = False
                exprs = [self._group_expr(tr, e, select_items)
                         for e in el.expressions]
                sets = [[]]
                for e in exprs:
                    sets = sets + [s + [e] for s in sets]
                grouping_sets = sets
                flat.extend(exprs)
            elif isinstance(el, t.GroupingSets):
                simple = False
                all_sets = []
                for gset in el.sets:
                    exprs = [self._group_expr(tr, e, select_items)
                             for e in gset]
                    all_sets.append(exprs)
                    flat.extend(exprs)
                grouping_sets = all_sets
            else:
                raise SemanticError("unsupported grouping element")
        # dedupe flat keys structurally
        uniq: List[RowExpression] = []
        for e in flat:
            if e not in uniq:
                uniq.append(e)

        # pre-projection: group keys + agg arguments + agg filters
        pre_assigns: List[Tuple[Symbol, RowExpression]] = []

        def to_symbol(expr: RowExpression, hint: str) -> Symbol:
            for s, e in pre_assigns:
                if e == expr:
                    return s
            if isinstance(expr, SymbolRef):
                sym = Symbol(expr.name, expr.type)
                pre_assigns.append((sym, expr))
                return sym
            sym = planner.symbols.new(hint, expr.type)
            pre_assigns.append((sym, expr))
            return sym

        key_syms: Dict[RowExpression, Symbol] = {}
        for e in uniq:
            key_syms[e] = to_symbol(e, "group")

        aggregations: List[Tuple[Symbol, AggCall]] = []
        for fc in agg_calls:
            name = fc.name.suffix.lower()
            args = tuple(tr.translate(a) for a in fc.args)
            resolved = resolve_aggregate(name, [a.type for a in args])
            args = tuple(cast_to(a, ty)
                         for a, ty in zip(args, resolved.arg_types))
            agg_name, distinct = resolved.name, fc.distinct
            if agg_name == "approx_distinct":
                # real HyperLogLog sketch (ops/aggregate._hll_grouped,
                # m=2048 -> 2.30% standard error); the optional
                # max-standard-error argument is advisory and dropped.
                # Reference: ApproximateCountDistinctAggregation.java
                args = args[:1]
            arg_syms = tuple(to_symbol(a, "aggarg") for a in args)
            filt_sym = None
            if fc.filter is not None:
                fx = tr.translate(fc.filter)
                filt_sym = to_symbol(fx, "aggfilter").ref()
            out_sym = planner.symbols.new(name, resolved.return_type)
            call = AggCall(agg_name,
                           tuple(s.ref() for s in arg_syms),
                           distinct, filt_sym,
                           args[0].type if args else None)
            aggregations.append((out_sym, call))
            # register substitution under the canonical aggregate key
            key = tr.aggregate_key(fc)
            self.substitutions[key] = out_sym

        if pre_assigns:  # count(*) with no keys needs no pre-projection
            self.node = ProjectNode(self.node, tuple(pre_assigns))

        group_symbols = tuple(key_syms[e] for e in uniq)
        if not simple and grouping_sets:
            sets_syms = tuple(
                tuple(key_syms[e] for e in gs) for gs in grouping_sets)
            gid = planner.symbols.new("groupid", T.BIGINT)
            passthrough = tuple(
                s for s, _ in pre_assigns if s not in group_symbols)
            self.node = GroupIdNode(self.node, sets_syms, gid, passthrough)
            self.node = AggregationNode(
                self.node, group_symbols + (gid,), tuple(aggregations))
            # grouping() in post-agg expressions decodes the set index
            self._grouping_info = (
                gid, [frozenset(s.name for s in gs) for gs in sets_syms])
        else:
            self.node = AggregationNode(self.node, group_symbols,
                                        tuple(aggregations))
        for e, s in key_syms.items():
            self.substitutions[e] = s
        # post-aggregation scope: original names resolve via substitutions,
        # so keep field list unchanged but symbols remapped where possible
        self._scope = Scope(self._scope.fields, self._scope.parent)

    def _group_expr(self, tr: ExpressionTranslator, e: t.Expression,
                    select_items) -> RowExpression:
        # GROUP BY <ordinal>
        if isinstance(e, t.LongLiteral):
            idx = e.value - 1
            if not 0 <= idx < len(select_items):
                raise SemanticError(f"GROUP BY position {e.value} out of range")
            return tr.translate(select_items[idx][0])
        return tr.translate(e)

    # ------------------------------------------------------------- windows

    def plan_windows(self, win_calls: List[t.FunctionCall]):
        planner = self.planner
        tr = self.translator()
        for fc in win_calls:
            w = fc.window
            name = fc.name.suffix.lower()
            if not (is_window(name) or is_aggregate(name)):
                raise SemanticError(f"not a window function: {name}")
            part_exprs = [tr.translate(e) for e in w.partition_by]
            order_items = [(tr.translate(s.key), s.ascending, s.nulls_first)
                           for s in w.order_by]
            # carry ALL current outputs (incl. previously planned window
            # symbols) through any pre-projection, not just scope fields —
            # a literal arg (lag(x, 2), ntile(3)) forces a ProjectNode and
            # must not drop earlier functions' outputs
            pre = [(s, s.ref()) for s in self.node.outputs]

            def sym_for(expr):
                for s, e in pre:
                    if e == expr:
                        return s
                s = planner.symbols.new("winkey", expr.type)
                pre.append((s, expr))
                return s

            part_syms = tuple(sym_for(e) for e in part_exprs)
            orderings = tuple(
                Ordering(sym_for(e), asc,
                         nf if nf is not None else not asc)
                for e, asc, nf in order_items)
            args = tuple(tr.translate(a) for a in fc.args)
            if name == "nth_value" and len(args) > 1 \
                    and isinstance(args[1], Literal) \
                    and args[1].value is not None \
                    and int(args[1].value) <= 0:
                # window/NthValueFunction parity: INVALID_FUNCTION_ARGUMENT
                raise SemanticError(
                    "Argument of NTH_VALUE must be greater than zero "
                    f"(actual value: {args[1].value})")
            arg_syms = tuple(sym_for(a).ref() for a in args)
            if any(not isinstance(e, SymbolRef) for _, e in pre):
                self.node = ProjectNode(self.node, tuple(pre))
            out_type = _window_type(name, args)
            out_sym = planner.symbols.new(name, out_type)
            frame = w.frame
            sv = (tr.translate(frame.start_value)
                  if frame and frame.start_value is not None else None)
            ev = (tr.translate(frame.end_value)
                  if frame and frame.end_value is not None else None)
            wf = WindowFunction(
                name, arg_syms,
                frame.frame_type if frame else "RANGE",
                frame.start_type if frame else "UNBOUNDED_PRECEDING",
                sv,
                (frame.end_type if frame and frame.end_type
                 else "CURRENT_ROW"),
                ev)
            self.node = WindowNode(self.node, part_syms, orderings,
                                   ((out_sym, wf),))
            self.substitutions[tr.aggregate_key(fc)] = out_sym

    # -------------------------------------------------------------- SELECT

    def plan_select(self, select_items, keep: Sequence[Symbol] = ()
                    ) -> List[Field]:
        """Project the select items; `keep` carries extra symbols (e.g.
        decorrelation join keys) through the projection."""
        tr = self.translator()
        assigns: List[Tuple[Symbol, RowExpression]] = [
            (s, s.ref()) for s in keep]
        fields: List[Field] = []
        for expr_ast, name in select_items:
            rx = tr.translate(expr_ast)
            # recompute after translate: select-list subqueries join extra
            # sources onto self.node as a translation side effect
            available = {s.name for s in self.node.outputs}
            missing = _symbols_in(rx) - available
            if missing:
                raise SemanticError(
                    f"'{expr_ast}' must be an aggregate expression or "
                    "appear in GROUP BY clause")
            if isinstance(rx, SymbolRef) and rx.name not in (
                    f.symbol.name for f in fields):
                sym = Symbol(rx.name, rx.type)
                assigns.append((sym, rx))
            else:
                # fresh symbol: non-trivial expression, or a second select
                # item resolving to an already-projected symbol (e.g. the
                # same aggregate under two aliases) — duplicate output
                # symbols are rejected by the plan validator
                sym = self.planner.symbols.new(name or "expr", rx.type)
                assigns.append((sym, rx))
            fields.append(Field(name, None, sym))
        self.node = ProjectNode(self.node, tuple(dict(
            (s.name, (s, e)) for s, e in assigns).values()))
        self._scope = Scope(fields, self._scope.parent)
        return fields

    def plan_distinct(self, out_fields: List[Field]):
        syms = tuple(f.symbol for f in out_fields)
        self.node = AggregationNode(self.node, syms, ())

    # ------------------------------------------------------------ ORDER BY

    def sort_key_source_symbols(self, sort_items) -> Tuple[Symbol, ...]:
        """Source symbols the ORDER BY needs that the SELECT list may not
        project — passed as `keep` through plan_select so sorting on
        unselected columns works (QueryPlanner ORDER BY scope)."""
        available = {s.name: s for s in self.node.outputs}
        keep: List[Symbol] = []
        tr = self.translator()
        for item in sort_items:
            if isinstance(item.key, t.LongLiteral):
                continue
            try:
                rx = tr.translate(item.key)
            except SemanticError:
                continue   # resolves only against output aliases
            for name in sorted(_symbols_in(rx)):
                sym = available.get(name)
                if sym is not None:
                    keep.append(sym)
        return tuple(dict.fromkeys(keep))

    def plan_order_by(self, sort_items: Tuple[t.SortItem, ...],
                      out_fields: List[Field],
                      pre_fields: Optional[List[Field]] = None):
        orderings: List[Ordering] = []
        extra: List[Tuple[Symbol, RowExpression]] = []
        # order-by scope: output aliases win, then the pre-projection scope
        for item in sort_items:
            sym = self._resolve_sort_key(item.key, out_fields, extra,
                                         pre_fields)
            nulls_first = item.nulls_first
            if nulls_first is None:
                nulls_first = not item.ascending  # Trino default
            orderings.append(Ordering(sym, item.ascending, nulls_first))
        if extra:
            assigns = [(s.name, (s, s.ref()))
                       for s in self.node.outputs]
            assigns += [(s.name, (s, e)) for s, e in extra]
            self.node = ProjectNode(self.node,
                                    tuple(dict(assigns).values()))
        self.node = SortNode(self.node, tuple(orderings))

    def _resolve_sort_key(self, key: t.Expression, out_fields: List[Field],
                          extra, pre_fields=None) -> Symbol:
        if isinstance(key, t.LongLiteral):
            idx = key.value - 1
            if not 0 <= idx < len(out_fields):
                raise SemanticError(
                    f"ORDER BY position {key.value} out of range")
            return out_fields[idx].symbol
        if isinstance(key, t.Identifier):
            matches = [f for f in out_fields if f.name == key.value]
            if len(matches) == 1:
                return matches[0].symbol
            if len(matches) > 1:
                raise SemanticError(f"ORDER BY '{key.value}' is ambiguous")
        # fall back: translate against the select-output scope (+ aggregate
        # substitutions). Output aliases win; the pre-projection scope
        # resolves source columns the SELECT list didn't project (their
        # symbols were carried through via plan_select's `keep`).
        parent = Scope(pre_fields, None) if pre_fields else None
        tr = ExpressionTranslator(
            Scope(out_fields, parent),
            self.substitutions, session=self.planner.session)
        rx = tr.translate(key)
        available = {s.name for s in self.node.outputs}
        missing = _symbols_in(rx) - available
        if missing:
            raise SemanticError(
                f"ORDER BY expression {key} references columns not in the "
                "select list")
        if isinstance(rx, SymbolRef):
            return Symbol(rx.name, rx.type)
        sym = self.planner.symbols.new("sortkey", rx.type)
        extra.append((sym, rx))
        return sym

    # -------------------------------------------------------- LIMIT/OFFSET

    def plan_offset(self, count: int):
        self.node = OffsetNode(self.node, count)

    def plan_limit(self, count: int):
        self.node = LimitNode(self.node, count)

    def prune_to(self, out_fields: List[Field]):
        want = tuple(f.symbol for f in out_fields)
        if tuple(self.node.outputs) != want:
            self.node = ProjectNode(
                self.node, tuple((s, s.ref()) for s in want))

    # ----------------------------------------------------------- subqueries

    def _handle_subquery(self, tr: ExpressionTranslator,
                         node: t.Expression) -> RowExpression:
        if isinstance(node, t.SubqueryExpression):
            return self._scalar_subquery(node)
        if isinstance(node, t.ExistsPredicate):
            return self._exists_subquery(node.subquery.query, negate=False)
        if isinstance(node, t.InPredicate):
            sub = node.value_list
            assert isinstance(sub, t.SubqueryExpression)
            return self._in_subquery(node.value, sub.query)
        raise SemanticError("unsupported subquery form")

    def _plan_subquery(self, query: t.Query) -> Tuple[RelationPlan, List]:
        """Plan a subquery against this scope as outer; collect correlated
        references (level, Field)."""
        correlated: List = []
        sub = self.planner._plan_query(query, self._scope, self.ctes)
        return sub, correlated

    def _scalar_subquery(self, node: t.SubqueryExpression) -> RowExpression:
        query = node.query
        decor = self._try_decorrelate_scalar_agg(query)
        if decor is not None:
            return decor
        sub = self.planner._plan_query(query, None, self.ctes)
        if len(sub.scope.fields) != 1:
            raise SemanticError("scalar subquery must return one column")
        enforced = EnforceSingleRowNode(sub.node)
        self.node = JoinNode(JoinKind.CROSS, self.node, enforced, ())
        return sub.scope.fields[0].symbol.ref()

    def _try_decorrelate_scalar_agg(self, query: t.Query
                                    ) -> Optional[RowExpression]:
        """min/avg/sum(...) correlated by equality -> group-by + LEFT join
        (TransformCorrelatedScalarAggregationToJoin)."""
        spec = query.body
        if not isinstance(spec, t.QuerySpecification) or query.with_ or \
                spec.group_by or spec.having or spec.limit or spec.offset \
                or spec.order_by or spec.from_ is None:
            return None
        split = self._split_correlation(spec)
        if split is None or not split[0]:
            return None  # uncorrelated or unsupported
        corr_pairs, local_where = split
        inner = self.planner._plan_relation(spec.from_, None, self.ctes)
        ib = _PlanBuilder(self.planner, inner, self.ctes)
        if local_where is not None:
            ib.plan_where(local_where)
        # single aggregate select item
        items = self.planner._expand_select(spec, ib.scope())
        if len(items) != 1:
            return None
        aggs = [fc for fc in _find_calls([items[0][0]])
                if is_aggregate(fc.name.suffix)]
        if len(aggs) == 0:
            return None
        # count-like aggregates yield 0 (not NULL) over an empty group; the
        # pre-aggregate-then-LEFT-join shape null-extends unmatched outer
        # rows, so a bare count must be COALESCE'd after the join. A count
        # buried in a larger select expression would need post-join
        # re-projection (the reference aggregates after the join instead) —
        # bail to the fail-loud path rather than return wrong results.
        _COUNT_LIKE = ("count", "count_if", "approx_distinct")
        has_count = any(fc.name.suffix.lower() in _COUNT_LIKE for fc in aggs)
        bare_agg = len(aggs) == 1 and items[0][0] is aggs[0]
        if has_count and not bare_agg:
            return None
        # inner grouping keys = inner sides of the correlation equalities
        inner_tr = ib.translator()
        inner_keys = [inner_tr.translate(ast) for _, ast in corr_pairs]
        # manually build aggregation grouped by correlation keys
        ib.plan_aggregation_with_keys(inner_keys, aggs, items)
        key_syms = ib.group_key_symbols
        out_fields = ib.plan_select(items, keep=key_syms)
        # LEFT join outer plan to the aggregated inner on the keys; the outer
        # side is cast to the inner key type (keys come from the same column
        # family in practice, so inner-type wins)
        outer_tr = self.translator()
        criteria = []
        probe = RelationPlan(self.node, self._scope)
        for (outer_ast, _), ksym in zip(corr_pairs, key_syms):
            ox = cast_to(outer_tr.translate(outer_ast), ksym.type)
            if isinstance(ox, SymbolRef):
                osym = Symbol(ox.name, ox.type)
            else:
                probe, osym = self.planner._append_projection(probe, ox)
            criteria.append(JoinClause(osym, ksym))
        # build side keeps key symbols + agg output
        build = ib.node
        self.node = JoinNode(JoinKind.LEFT, probe.node, build,
                             tuple(criteria))
        self._scope = Scope(probe.scope.fields, self._scope.parent)
        out = out_fields[0].symbol.ref()
        if has_count:
            # TransformCorrelatedScalarAggregationToJoin semantics: outer
            # rows with no matching inner rows see count(...) = 0
            out = SpecialForm(SpecialKind.COALESCE,
                              (out, Literal(0, out.type)), out.type)
        return out

    def _split_correlation(self, spec: t.QuerySpecification):
        """WHERE -> ([(outer_ast, inner_ast)], local_where_ast or None).

        Returns None when correlation exists but isn't equality-only
        (unsupported this round).
        """
        if spec.where is None:
            return [], None
        inner_scope_probe = self._inner_name_probe(spec)
        corr: List[Tuple[t.Expression, t.Expression]] = []
        local: List[t.Expression] = []
        def orient(eq):
            a, b = eq.left, eq.right
            if self._classify(a, inner_scope_probe) == "local":
                corr.append((b, a))   # (outer side, inner side)
            else:
                corr.append((a, b))

        for conj in _conjuncts(spec.where):
            side = self._classify(conj, inner_scope_probe)
            if side == "local":
                local.append(conj)
            elif side == "corr_eq":
                orient(conj)
            else:
                # (E AND L1) OR (E AND L2) with one shared correlation
                # equality E factors to E AND (L1 OR L2) — the TPC-DS q41
                # shape (TransformCorrelated* handles this via general
                # subquery planning in the reference)
                factored = self._factor_or_correlation(
                    conj, inner_scope_probe)
                if factored is None:
                    return None
                eqs, local_or = factored
                for eq in eqs:
                    orient(eq)
                local.append(local_or)
        where = None
        if local:
            where = local[0]
            for c in local[1:]:
                where = t.LogicalBinary("AND", where, c)
        return corr, where

    def _factor_or_correlation(self, conj, probe):
        """(E... AND L1) OR (E... AND L2) -> ([E...], L1 OR L2) when every
        disjunct carries the structurally-identical correlation
        equalities; None otherwise."""
        if not (isinstance(conj, t.LogicalBinary) and conj.op == "OR"):
            return None

        def disjuncts(e):
            if isinstance(e, t.LogicalBinary) and e.op == "OR":
                return disjuncts(e.left) + disjuncts(e.right)
            return [e]

        shared_key = None
        shared_eqs = None
        locals_ = []
        for d in disjuncts(conj):
            eqs, rest = [], []
            for c in _conjuncts(d):
                side = self._classify(c, probe)
                if side == "corr_eq":
                    eqs.append(c)
                elif side == "local":
                    rest.append(c)
                else:
                    return None
            key = tuple(sorted(repr(e) for e in eqs))
            if shared_key is None:
                shared_key, shared_eqs = key, eqs
            elif key != shared_key:
                return None
            locals_.append(_combine_ast(rest) if rest
                           else t.BooleanLiteral(True))
        if not shared_eqs:
            return None
        out = locals_[0]
        for x in locals_[1:]:
            out = t.LogicalBinary("OR", out, x)
        return shared_eqs, out

    def _inner_name_probe(self, spec: t.QuerySpecification):
        """Set of column names/qualifiers visible inside the subquery FROM."""
        probe = self.planner._plan_relation(spec.from_, None, self.ctes)
        names = set()
        quals = set()
        for f in probe.scope.fields:
            if f.name:
                names.add(f.name)
            if f.qualifier:
                quals.add(f.qualifier)
        return names, quals

    def _classify(self, e: t.Expression, probe) -> str:
        """'local' (inner-only), 'corr_eq' (equality inner=outer), 'other'."""
        names, quals = probe
        refs_inner = False
        refs_outer = False
        for parts in self._column_refs(e):
            if len(parts) >= 2:
                (refs_inner, refs_outer) = (
                    (True, refs_outer) if parts[-2] in quals
                    else (refs_inner, True))
            elif parts[0] in names:
                refs_inner = True
            elif self._scope.try_resolve(parts) is not None:
                refs_outer = True
        if not refs_outer:
            return "local"
        if isinstance(e, t.ComparisonExpression) and e.op == "=":
            ls = self._classify(e.left, probe)
            rs = self._classify(e.right, probe)
            # only a clean inner=outer split is a correlation key; a mixed
            # side (references both scopes) would silently rebind an
            # unqualified inner column against the outer scope
            if {ls, rs} == {"local", "outer_only"}:
                return "corr_eq"
        if not refs_inner:
            return "outer_only"
        return "other"

    @staticmethod
    def _column_refs(e: t.Expression):
        """Column references as qualified-name tuples; a dereference's
        component identifiers are NOT yielded separately (t1.rk must not
        read as a bare `rk` — that aliased inner fields named rk onto the
        outer side and killed the q01-shape decorrelation)."""
        stack = [e]
        out = []
        while stack:
            n = stack.pop()
            if isinstance(n, t.DereferenceExpression):
                from trino_tpu.planner.translate import _dereference_parts
                parts = _dereference_parts(n)
                if parts is not None:
                    out.append(parts)
                    continue
            if isinstance(n, t.Identifier):
                out.append((n.value,))
                continue
            if isinstance(n, (t.SubqueryExpression, t.ExistsPredicate)):
                continue
            stack.extend(_ast_children(n))
        return out

    def _exists_subquery(self, query: t.Query, negate: bool) -> RowExpression:
        spec = query.body
        if not isinstance(spec, t.QuerySpecification) or spec.from_ is None:
            raise SemanticError("unsupported EXISTS subquery")
        # GROUP BY / HAVING / LIMIT / aggregates change EXISTS cardinality
        # semantics (e.g. HAVING count(*) > 5, LIMIT 0, global agg always
        # emitting one row); the translation below would silently drop them
        if spec.group_by or spec.having or spec.limit or spec.offset or any(
                is_aggregate(fc.name.suffix)
                for fc in _find_calls([i.expression
                                       for i in spec.select.items
                                       if isinstance(i, t.SingleColumn)])):
            raise SemanticError(
                "EXISTS subquery with GROUP BY/HAVING/LIMIT/OFFSET/"
                "aggregates not supported")
        split = self._split_correlation(spec)
        if split is None:
            # correlation beyond clean equalities (e.g. q21's
            # l2.l_suppkey <> l1.l_suppkey): general decorrelation
            return self._exists_general(spec, negate)
        corr_pairs, local_where = split
        inner = self.planner._plan_relation(spec.from_, None, self.ctes)
        ib = _PlanBuilder(self.planner, inner, self.ctes)
        if local_where is not None:
            ib.plan_where(local_where)
        if not corr_pairs:
            # uncorrelated EXISTS: cross join against (SELECT count(*) > 0)
            cnt = self.planner.symbols.new("cnt", T.BIGINT)
            agg = AggregationNode(
                ib.node, (), ((cnt, AggCall("count", (), False, None, None)),))
            flag = self.planner.symbols.new("exists", T.BOOLEAN)
            proj = ProjectNode(agg, ((flag, Call(
                "gt", (cnt.ref(), Literal(0, T.BIGINT)), T.BOOLEAN)),))
            self.node = JoinNode(JoinKind.CROSS, self.node, proj, ())
            out = flag.ref()
            return SpecialForm(SpecialKind.NOT, (out,), T.BOOLEAN) \
                if negate else out
        # correlated: semi join on the correlation keys
        inner_tr = ib.translator()
        inner_keys = [inner_tr.translate(iast) for _, iast in corr_pairs]
        outer_tr = self.translator()
        outer_keys = [outer_tr.translate(oast) for oast, _ in corr_pairs]
        return self._semi_join(outer_keys, inner_keys, ib, negate,
                               null_aware=False)

    def _exists_general(self, spec: t.QuerySpecification,
                        negate: bool) -> RowExpression:
        """EXISTS with arbitrary correlated predicates.

        TransformCorrelatedExistsSubquery's general shape: tag each outer row
        with a unique id, inner-join outer x subquery-FROM under the full
        correlated predicate (equalities become hash-join criteria via
        PredicatePushDown; the rest stays a join filter), then semi-join the
        outer rows against the surviving ids. NOT EXISTS = anti on the same
        set. Deterministic scan order makes the ids stable across the two
        traversals of the outer subtree."""
        planner = self.planner
        inner = planner._plan_relation(spec.from_, None, self.ctes)
        ib = _PlanBuilder(planner, inner, self.ctes)
        probe_names = self._inner_name_probe(spec)
        local: List[t.Expression] = []
        mixed: List[t.Expression] = []
        for conj in _conjuncts(spec.where) if spec.where is not None else []:
            if self._classify(conj, probe_names) == "local":
                local.append(conj)
            else:
                mixed.append(conj)
        if local:
            where = local[0]
            for c in local[1:]:
                where = t.LogicalBinary("AND", where, c)
            ib.plan_where(where)
        if not mixed:
            raise SemanticError("unsupported EXISTS subquery")
        uid = planner.symbols.new("unique", T.BIGINT)
        probe_node = AssignUniqueIdNode(self.node, uid)
        joined = JoinNode(JoinKind.CROSS, probe_node, ib.node, ())
        combined = Scope(list(self._scope.fields) + list(ib.scope().fields),
                         self._scope.parent)
        tr = ExpressionTranslator(combined, {},
                                  subquery_handler=self._handle_subquery,
                                  session=planner.session)
        pred = None
        for conj in mixed:
            rx = tr.translate(conj)
            if not isinstance(rx.type, T.BooleanType):
                raise SemanticError("EXISTS predicate must be boolean")
            pred = rx if pred is None else SpecialForm(
                SpecialKind.AND, (pred, rx), T.BOOLEAN)
        filtered = FilterNode(joined, pred)
        proj = ProjectNode(filtered, ((uid, uid.ref()),))
        match = planner.symbols.new("match", T.BOOLEAN)
        self.node = SemiJoinNode(probe_node, proj, (uid,), (uid,), match,
                                 negate, null_aware=False)
        out = match.ref()
        return SpecialForm(SpecialKind.NOT, (out,), T.BOOLEAN) \
            if negate else out

    def _in_subquery(self, value_ast: t.Expression,
                     query: t.Query) -> RowExpression:
        sub = self.planner._plan_query(query, None, self.ctes)
        if len(sub.scope.fields) != 1:
            raise SemanticError("IN subquery must return one column")
        ib = _PlanBuilder(self.planner,
                          RelationPlan(sub.node, sub.scope), self.ctes)
        outer_tr = self.translator()
        v = outer_tr.translate(value_ast)
        return self._semi_join([v], [sub.scope.fields[0].symbol.ref()], ib,
                               negate=False, null_aware=True)

    def _semi_join(self, outer_keys: List[RowExpression],
                   inner_keys: List[RowExpression], ib: "_PlanBuilder",
                   negate: bool, null_aware: bool = True) -> RowExpression:
        planner = self.planner
        # coerce pairwise
        okeys, ikeys = [], []
        for o, i in zip(outer_keys, inner_keys):
            o2, i2 = planner._coerce_join_keys(o, i)
            okeys.append(o2)
            ikeys.append(i2)
        probe = RelationPlan(self.node, self._scope)
        probe_syms = []
        for o in okeys:
            if isinstance(o, SymbolRef):
                probe_syms.append(Symbol(o.name, o.type))
            else:
                probe, s = planner._append_projection(probe, o)
                probe_syms.append(s)
        build_plan = RelationPlan(ib.node, ib.scope())
        build_syms = []
        for i in ikeys:
            if isinstance(i, SymbolRef):
                build_syms.append(Symbol(i.name, i.type))
            else:
                build_plan, s = planner._append_projection(build_plan, i)
                build_syms.append(s)
        match = planner.symbols.new("match", T.BOOLEAN)
        self.node = SemiJoinNode(
            probe.node, build_plan.node, tuple(probe_syms),
            tuple(build_syms), match, negate, null_aware)
        self._scope = Scope(probe.scope.fields, self._scope.parent)
        out = match.ref()
        if negate:
            return SpecialForm(SpecialKind.NOT, (out,), T.BOOLEAN)
        return out

    # -------------------------------------------- decorrelation helper API

    def plan_aggregation_with_keys(self, key_exprs: List[RowExpression],
                                   agg_calls, select_items):
        """Aggregation grouped by explicit key expressions (decorrelation)."""
        planner = self.planner
        tr = self.translator()
        pre_assigns: List[Tuple[Symbol, RowExpression]] = []

        def to_symbol(expr, hint):
            for s, e in pre_assigns:
                if e == expr:
                    return s
            if isinstance(expr, SymbolRef):
                sym = Symbol(expr.name, expr.type)
                pre_assigns.append((sym, expr))
                return sym
            sym = planner.symbols.new(hint, expr.type)
            pre_assigns.append((sym, expr))
            return sym

        key_syms = [to_symbol(e, "corrkey") for e in key_exprs]
        aggregations = []
        for fc in agg_calls:
            name = fc.name.suffix.lower()
            args = tuple(tr.translate(a) for a in fc.args)
            resolved = resolve_aggregate(name, [a.type for a in args])
            args = tuple(cast_to(a, ty)
                         for a, ty in zip(args, resolved.arg_types))
            agg_name, distinct = resolved.name, fc.distinct
            if agg_name == "approx_distinct":
                # HLL sketch; advisory error argument dropped
                args = args[:1]
            arg_syms = tuple(to_symbol(a, "aggarg") for a in args)
            out_sym = planner.symbols.new(name, resolved.return_type)
            aggregations.append((out_sym, AggCall(
                agg_name, tuple(s.ref() for s in arg_syms),
                distinct, None, args[0].type if args else None)))
            self.substitutions[tr.aggregate_key(fc)] = out_sym
        self.node = ProjectNode(self.node, tuple(pre_assigns))
        self.node = AggregationNode(self.node, tuple(key_syms),
                                    tuple(aggregations))
        self.group_key_symbols = key_syms


def _window_type(name: str, args) -> T.Type:
    n = name.lower()
    if n in ("row_number", "rank", "dense_rank", "ntile"):
        return T.BIGINT
    if n in ("percent_rank", "cume_dist"):
        return T.DOUBLE
    if n in ("lag", "lead", "first_value", "last_value", "nth_value"):
        return args[0].type if args else T.BIGINT
    if is_aggregate(n):
        return resolve_aggregate(n, [a.type for a in args]).return_type
    return T.BIGINT
