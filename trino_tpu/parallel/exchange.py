"""Collective exchanges: the shuffle data plane as ICI collectives.

Reference parity (SURVEY §2.8): PartitionedOutputOperator + OutputBuffer +
HttpPageBufferClient + ExchangeClient — all replaced by in-program
collectives. These functions run INSIDE a shard_map over QueryMesh.AXIS:

  all_to_all_by_key : FIXED_HASH_DISTRIBUTION repartition. Rows are radix-
                      bucketed by key hash, compacted per destination, and
                      exchanged with lax.all_to_all. Fixed per-peer bucket
                      capacity keeps shapes static; the returned overflow
                      count is psum'd so the host can re-run with a larger
                      bucket (same contract as the join/page capacity ladder).
  broadcast_page    : FIXED_BROADCAST — all_gather the build side.
  gather_page       : SINGLE distribution — all_gather + shard-0 consumption
                      (coordinator-only stages read one replica).

Hash function matches ops/join._mix64 (splitmix64) so co-partitioned joins
land build/probe rows of one key on one shard.

Skew (JSPIM heavy-hitter-aware partitioning): plain hash routing sends
EVERY row of one hot key to one shard — a single skewed key overloads a
chip while the rest idle (TPC-DS catalog/web fact joins). detect_heavy_keys
finds globally-frequent keys in-program (local run lengths -> top-k
candidates -> all_gather -> global counts); the join exchange then SPREADS
heavy probe rows round-robin across the mesh and REPLICATES the matching
build rows to every shard, so correctness is preserved (each probe row
still sees all of its key's build rows exactly once) while no shard
receives more than ~1/n of a hot key's probe rows.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from trino_tpu.ops.join import _key_u64, _mix64
from trino_tpu.page import Column, Page

AXIS = "workers"

_U64MAX = jnp.uint64(0xFFFFFFFFFFFFFFFF)


def _is_heavy(key: jnp.ndarray, heavy: jnp.ndarray) -> jnp.ndarray:
    """Row mask: key value appears in the (sentinel-padded) heavy set."""
    hk = heavy[None, :]
    return ((key[:, None] == hk) & (hk != _U64MAX)).any(axis=1)


def _partition_of(page: Page, key_channels: Sequence[int],
                  n_parts: int,
                  heavy: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    key, is_null = _key_u64(page, key_channels)
    part = (_mix64(key) % jnp.uint64(n_parts)).astype(jnp.int32)
    # null keys route to shard 0 (they never match joins/group as equals is
    # handled downstream; they just need a deterministic home)
    part = jnp.where(is_null, 0, part)
    if heavy is not None:
        # spread mode: rows of a heavy key round-robin over the mesh by
        # row position instead of hammering the key's hash shard
        idx = jnp.arange(page.capacity, dtype=jnp.uint64)
        spread = ((_mix64(key) + idx) % jnp.uint64(n_parts)) \
            .astype(jnp.int32)
        part = jnp.where(_is_heavy(key, heavy) & ~is_null, spread, part)
    return jnp.where(page.row_mask(), part, n_parts)  # dead rows -> dropped


def detect_heavy_keys(page: Page, key_channels: Sequence[int], k: int,
                      min_global_count: int, axis: str = AXIS
                      ) -> jnp.ndarray:
    """Globally-frequent key detection, entirely in-program (JSPIM's
    heavy-hitter pre-pass as a collective): each shard sorts its keys,
    takes its k most frequent as candidates, all_gathers the (n*k)
    candidate (key, count) pairs, and sums counts across shards per
    candidate. Returns a [k] uint64 vector of raw key values whose global
    count reaches min_global_count, padded with the u64 sentinel.

    A truly heavy key is in the local top-k of every shard where it is
    frequent, so the global sum is exact for the keys that matter;
    borderline keys may be undercounted and simply stay un-spread."""
    n = jax.lax.psum(1, axis)
    key, is_null = _key_u64(page, key_channels)
    live = page.row_mask() & ~is_null
    masked = jnp.where(live, key, _U64MAX)
    s = jnp.sort(masked)
    cap = page.capacity
    idx = jnp.arange(cap, dtype=jnp.int32)
    boundary = (s != jnp.roll(s, 1)).at[0].set(True)
    run_start = jax.lax.cummax(jnp.where(boundary, idx, 0))
    nxt = jnp.where(boundary, idx, cap)
    suffix_min = jnp.flip(jax.lax.cummin(jnp.flip(nxt)))
    next_start = jnp.concatenate(
        [suffix_min[1:], jnp.full((1,), cap, dtype=suffix_min.dtype)])
    run_len = (next_start - run_start).astype(jnp.int32)
    cand_count = jnp.where(boundary & (s != _U64MAX), run_len, 0)
    top_counts, top_idx = jax.lax.top_k(cand_count, k)
    cand_keys = jnp.take(s, top_idx)
    all_keys = jax.lax.all_gather(cand_keys, axis).reshape(n * k)
    all_counts = jax.lax.all_gather(top_counts, axis).reshape(n * k)
    eq = all_keys[:, None] == all_keys[None, :]
    glob = jnp.sum(eq * all_counts[None, :].astype(jnp.int64), axis=1)
    nk = n * k
    first = ~jnp.any(eq & (jnp.arange(nk)[None, :] < jnp.arange(nk)[:, None]),
                     axis=1)
    score = jnp.where((all_keys != _U64MAX) & first
                      & (glob >= min_global_count), glob, -1)
    sel_score, sel = jax.lax.top_k(score, k)
    return jnp.where(sel_score > 0, jnp.take(all_keys, sel), _U64MAX)


def _exchange_compact(cols, occ, n: int, bucket_capacity: int,
                      axis: str) -> Page:
    """The receive half of an all_to_all exchange: swap the per-destination
    buckets over the mesh, mask validity by received occupancy, and compact
    live rows to a dense prefix so downstream operators see a normal page."""
    def a2a(x):
        return jax.lax.all_to_all(
            x.reshape(n, bucket_capacity, *x.shape[1:]), axis,
            split_axis=0, concat_axis=0).reshape(n * bucket_capacity,
                                                 *x.shape[1:])

    occ_recv = a2a(occ)
    out_cols = []
    for c in cols:
        vals = a2a(c.values)
        valid = a2a(c.valid) & occ_recv
        out_cols.append(Column(vals, valid if c.valid is not None else None,
                               c.type, c.dictionary))

    perm = jnp.argsort(~occ_recv, stable=True)
    num = jnp.sum(occ_recv).astype(jnp.int32)
    out_cols = [Column(jnp.take(c.values, perm),
                       None if c.valid is None else jnp.take(c.valid, perm),
                       c.type, c.dictionary)
                for c in out_cols]
    return Page(tuple(out_cols), num)


def all_to_all_by_key(page: Page, key_channels: Sequence[int],
                      bucket_capacity: int, axis: str = AXIS,
                      heavy: Optional[jnp.ndarray] = None
                      ) -> Tuple[Page, jnp.ndarray]:
    """Hash-repartition rows across the mesh axis.

    Returns (page_of_rows_now_owned_by_this_shard, global_overflow_count).
    Overflow > 0 means some source shard had more than bucket_capacity rows
    for one destination; the host re-runs the stage with a bigger bucket.

    `heavy` (optional [k] uint64 from detect_heavy_keys) engages SPREAD
    mode: rows of heavy keys round-robin across all shards instead of hash
    routing — the probe half of the skew-aware join exchange (the build
    half replicates via all_to_all_replicate with the SAME heavy set).
    """
    n = jax.lax.psum(1, axis)
    part = _partition_of(page, key_channels, n, heavy=heavy)

    # stable sort rows by destination, then slot rows into per-destination
    # fixed-capacity buckets: position within bucket = rank within partition
    order = jnp.argsort(part, stable=True)
    part_sorted = jnp.take(part, order)
    idx = jnp.arange(page.capacity, dtype=jnp.int32)
    # rank within run of equal destinations
    start_of_run = jnp.searchsorted(part_sorted, jnp.arange(
        n + 1, dtype=part_sorted.dtype))
    rank = idx - jnp.take(start_of_run,
                          part_sorted.astype(jnp.int32).clip(0, n))
    counts = jnp.diff(start_of_run)  # rows per destination
    overflow_local = jnp.sum(jnp.maximum(counts - bucket_capacity, 0))

    live = (part_sorted < n) & (rank < bucket_capacity)
    slot = part_sorted.astype(jnp.int32).clip(0, n - 1) * bucket_capacity + \
        jnp.minimum(rank, bucket_capacity - 1)
    # dead/overflow rows must not clobber occupied slots: send them
    # out-of-bounds where scatter mode="drop" discards them
    slot = jnp.where(live, slot, n * bucket_capacity)

    send_rows = jnp.take(order, idx)  # row index per sorted position

    def scatter_col(col: Column) -> Column:
        vals = jnp.take(col.values, send_rows)
        buf = jnp.zeros((n * bucket_capacity,), dtype=col.values.dtype)
        buf = buf.at[slot].set(vals, mode="drop")
        valid_buf = jnp.zeros((n * bucket_capacity,), dtype=jnp.bool_)
        src_valid = live
        if col.valid is not None:
            src_valid = live & jnp.take(col.valid, send_rows)
        valid_buf = valid_buf.at[slot].set(src_valid, mode="drop")
        return Column(buf, valid_buf, col.type, col.dictionary)

    # occupancy mask rides as an extra column so receivers know live rows
    occ = jnp.zeros((n * bucket_capacity,), dtype=jnp.bool_)
    occ = occ.at[slot].set(live, mode="drop")

    cols = [scatter_col(c) for c in page.columns]
    out = _exchange_compact(cols, occ, n, bucket_capacity, axis)
    total_overflow = jax.lax.psum(overflow_local, axis)
    return out, total_overflow


def all_to_all_replicate(page: Page, key_channels: Sequence[int],
                         bucket_capacity: int, heavy: jnp.ndarray,
                         axis: str = AXIS) -> Tuple[Page, jnp.ndarray]:
    """Skew-aware build-side repartition: rows of non-heavy keys hash-route
    as usual; rows of heavy keys are REPLICATED into every destination's
    bucket, so each shard holds the full build set for the keys whose probe
    rows were spread across the mesh (JSPIM heavy-hitter replication).

    Returns (page, global_overflow_count) with the same overflow-ladder
    contract as all_to_all_by_key."""
    n = jax.lax.psum(1, axis)
    key, is_null = _key_u64(page, key_channels)
    live = page.row_mask()
    hpart = (_mix64(key) % jnp.uint64(n)).astype(jnp.int32)
    hpart = jnp.where(is_null, 0, hpart)
    hvy = _is_heavy(key, heavy) & ~is_null
    total_slots = n * bucket_capacity
    overflow_local = jnp.int32(0)
    dests = []
    for d in range(n):
        m = live & ((hpart == d) | hvy)
        rank = jnp.cumsum(m) - 1
        cnt = jnp.sum(m)
        overflow_local = overflow_local + jnp.maximum(
            cnt - bucket_capacity, 0).astype(jnp.int32)
        ok = m & (rank < bucket_capacity)
        slot = jnp.where(ok, d * bucket_capacity + rank, total_slots)
        dests.append((slot, ok))

    def scatter_col(col: Column) -> Column:
        buf = jnp.zeros((total_slots,), dtype=col.values.dtype)
        vbuf = jnp.zeros((total_slots,), dtype=jnp.bool_)
        for slot, ok in dests:
            buf = buf.at[slot].set(col.values, mode="drop")
            src_valid = ok
            if col.valid is not None:
                src_valid = ok & col.valid
            vbuf = vbuf.at[slot].set(src_valid, mode="drop")
        return Column(buf, vbuf, col.type, col.dictionary)

    occ = jnp.zeros((total_slots,), dtype=jnp.bool_)
    for slot, ok in dests:
        occ = occ.at[slot].set(ok, mode="drop")
    cols = [scatter_col(c) for c in page.columns]
    out = _exchange_compact(cols, occ, n, bucket_capacity, axis)
    return out, jax.lax.psum(overflow_local, axis)


def broadcast_page(page: Page, axis: str = AXIS) -> Page:
    """Replicate every shard's rows to all shards (build-side broadcast).

    Output capacity = n * input capacity; rows keep their liveness via the
    row-count scalar recomputed from per-shard counts.
    """
    n = jax.lax.psum(1, axis)
    my_rows = page.num_rows

    def gather(x):
        g = jax.lax.all_gather(x, axis)  # (n, cap, ...)
        return g.reshape(n * x.shape[0], *x.shape[1:])

    rows_per_shard = jax.lax.all_gather(my_rows, axis)  # (n,)
    cap = page.capacity
    idx = jnp.arange(n * cap, dtype=jnp.int32)
    shard_of = idx // cap
    within = idx % cap
    live = within < jnp.take(rows_per_shard, shard_of)
    cols = []
    for c in page.columns:
        vals = gather(c.values)
        valid = None
        if c.valid is not None:
            valid = gather(c.valid) & live
        cols.append(Column(vals, valid, c.type, c.dictionary))
    # compact live rows to the front
    perm = jnp.argsort(~live, stable=True)
    cols = [Column(jnp.take(c.values, perm),
                   None if c.valid is None else jnp.take(c.valid, perm),
                   c.type, c.dictionary) for c in cols]
    return Page(tuple(cols), jnp.sum(rows_per_shard).astype(jnp.int32))


def gather_page(page: Page, axis: str = AXIS) -> Page:
    """SINGLE distribution: every shard receives all rows; the host reads
    shard 0's replica (coordinator-only consumption)."""
    return broadcast_page(page, axis)
