"""Columnar Page/Column data model as JAX pytrees.

Reference parity: core/trino-spi/src/main/java/io/trino/spi/Page.java:33 and
spi/block/ (68 files). Design decisions (SURVEY.md §7.1):

- A Column = device value array + optional validity mask (replaces the Block
  hierarchy: nulls-as-bitmask instead of null flags per block kind).
- Strings are dictionary-encoded (spi/block/DictionaryBlock analog): device
  holds int32 codes; the host-side Dictionary holds the sorted string pool, so
  comparisons and ORDER BY on codes match string collation.
- A Page = tuple of equal-capacity Columns + a traced `num_rows` scalar. XLA
  needs static shapes, so pages have a static *capacity* (array length) and a
  dynamic row count; rows in [num_rows, capacity) are padding. Filters compact
  via a stable flag-sort (Page.filter), the device analog of
  Page.getPositions (spi/Page.java:332) / Block.copyPositions.
- Columns/Pages are registered pytrees so whole operator pipelines jit/shard
  cleanly; Type and Dictionary ride as static aux data (hash/eq by content
  fingerprint for dictionaries, so repeated pages of one table — and any
  OTHER table with a byte-identical pool — never retrace).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T

_dict_ids = itertools.count()


class Dictionary:
    """Host-side sorted string pool backing a dictionary-encoded column.

    Codes are indices into `values` (np.ndarray of str, ascending order), so
    integer comparison of codes == string comparison of values. Code -1 is
    reserved for padding. Hash/eq key on a CONTENT fingerprint so the pool
    can ride as jit-static aux data without object identity fragmenting
    the trace cache: two tables whose string pools are byte-identical
    (same data loaded twice, a re-created memory table, a re-generated
    connector pool) hit ONE trace for a warm canonical kernel instead of
    retracing per Dictionary object. Correctness: every host-side fold a
    trace bakes in (code_of, bounds, like/transform tables) is a pure
    function of the pool CONTENT, so content-equal pools are
    interchangeable within a trace. Eq compares fingerprints only — a
    16-byte blake2b over the pool — so trace-cache lookups stay O(1)
    instead of O(pool).
    """

    __slots__ = ("values", "id", "_table_cache", "_fp")

    def __init__(self, values: np.ndarray):
        import hashlib
        values = np.asarray(values, dtype=object)
        # ONE pass fuses the sortedness check (what makes device-side
        # <,>,min,max on codes correct) with the content fingerprint:
        # hashing at construction time means the pool bytes are walked
        # exactly once, while they are cache-hot from being built — a
        # lazily-hashed multi-GB pool used to stall the FIRST prepared
        # EXECUTE over a large string table by multiple milliseconds at
        # its first trace-cache lookup.
        h = hashlib.blake2b(digest_size=16)
        prev = None
        for s in values:
            if prev is not None and not (prev <= s):
                raise ValueError("dictionary must be sorted")
            prev = s
            b = s.encode("utf-8", "surrogatepass") \
                if isinstance(s, str) else repr(s).encode()
            h.update(len(b).to_bytes(4, "little"))
            h.update(b)
        self.values = values
        self.id = next(_dict_ids)
        self._fp = h.digest()   # content fingerprint, fixed at build

    @property
    def fingerprint(self) -> bytes:
        """Content digest of the pool (computed incrementally at
        construction): the jit-static identity of this dictionary."""
        return self._fp

    @classmethod
    def build(cls, strings: Sequence[str]) -> Tuple["Dictionary", np.ndarray]:
        """Encode `strings` -> (dictionary, int32 codes)."""
        uniq, codes = np.unique(np.asarray(strings, dtype=object),
                                return_inverse=True)
        return cls(uniq), codes.astype(np.int32)

    def code_of(self, s: str) -> int:
        """Exact-match lookup; -1 if absent (used to fold literals)."""
        i = int(np.searchsorted(self.values, s))
        if i < len(self.values) and self.values[i] == s:
            return i
        return -1

    def lower_bound(self, s: str) -> int:
        return int(np.searchsorted(self.values, s, side="left"))

    def upper_bound(self, s: str) -> int:
        return int(np.searchsorted(self.values, s, side="right"))

    def encode(self, strings: np.ndarray) -> np.ndarray:
        """Map strings -> int32 codes; raises KeyError if any value is absent."""
        arr = np.asarray(strings, dtype=object)
        if len(self.values) == 0:
            if len(arr) == 0:
                return np.empty(0, dtype=np.int32)
            raise KeyError("value(s) not present in dictionary")
        codes = np.searchsorted(self.values, arr).astype(np.int32)
        codes = np.minimum(codes, len(self.values) - 1)
        if not np.array_equal(self.values[codes], arr):
            raise KeyError("value(s) not present in dictionary")
        return codes

    def decode(self, codes: np.ndarray) -> np.ndarray:
        out = np.empty(len(codes), dtype=object)
        valid = codes >= 0
        out[valid] = self.values[codes[valid]]
        out[~valid] = None
        return out

    def __len__(self):
        return len(self.values)

    def __hash__(self):
        return hash(self.fingerprint)

    def __eq__(self, other):
        if self is other:
            return True
        if not isinstance(other, Dictionary):
            return NotImplemented
        return self.fingerprint == other.fingerprint

    def __repr__(self):  # pragma: no cover
        return f"Dictionary(id={self.id}, n={len(self.values)})"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Column:
    """One columnar vector. Reference: spi/block/Block.java:25.

    values : device array [capacity] of type.dtype — or, for ARRAY/MAP
             list layouts, [capacity, max_len] element planes
    valid  : optional bool device array [capacity]; None = no nulls
    type   : SQL Type (static)
    dictionary : for string types, the host string pool (static)
    lengths: for list layouts, int32 [capacity] live element counts
    aux    : for MAP, the per-element value plane [capacity, max_len]
             (keys live in `values` so map lookups search sorted keys)
    """

    values: jnp.ndarray
    valid: Optional[jnp.ndarray]
    type: T.Type
    dictionary: Optional[Dictionary] = None
    lengths: Optional[jnp.ndarray] = None
    aux: Optional[jnp.ndarray] = None
    aux_dictionary: Optional[Dictionary] = None

    def tree_flatten(self):
        children = [self.values]
        flags = [False, False]
        if self.valid is not None:
            children.append(self.valid)
            flags[0] = True
        extra = 0
        if self.lengths is not None:
            children.append(self.lengths)
            extra = 1
            if self.aux is not None:
                children.append(self.aux)
                extra = 2
        flags[1] = extra
        return tuple(children), (flags[0], flags[1], self.type,
                                 self.dictionary, self.aux_dictionary)

    @classmethod
    def tree_unflatten(cls, aux, children):
        has_valid, extra, typ, dictionary, aux_dict = aux
        it = iter(children)
        values = next(it)
        valid = next(it) if has_valid else None
        lengths = next(it) if extra >= 1 else None
        aux_arr = next(it) if extra >= 2 else None
        return cls(values, valid, typ, dictionary, lengths, aux_arr,
                   aux_dict)

    @property
    def capacity(self) -> int:
        return self.values.shape[0]

    def valid_mask(self) -> jnp.ndarray:
        """Always-materialized validity mask."""
        if self.valid is None:
            return jnp.ones(self.capacity, dtype=jnp.bool_)
        return self.valid

    def gather(self, indices: jnp.ndarray) -> "Column":
        """copyPositions analog (Block.java:250).

        Out-of-range indices clip to the last row: padding rows of a filtered
        page are garbage copies of a live row. INVARIANT: consumers must mask
        with Page.row_mask() — num_rows, not validity, delimits live rows.
        """
        values = jnp.take(self.values, indices, axis=0, mode="clip")
        valid = None
        if self.valid is not None:
            valid = jnp.take(self.valid, indices, mode="clip")
        lengths = None if self.lengths is None else \
            jnp.take(self.lengths, indices, mode="clip")
        aux = None if self.aux is None else \
            jnp.take(self.aux, indices, axis=0, mode="clip")
        return Column(values, valid, self.type, self.dictionary, lengths,
                      aux, self.aux_dictionary)

    def with_valid(self, valid: Optional[jnp.ndarray]) -> "Column":
        return Column(self.values, valid, self.type, self.dictionary,
                      self.lengths, self.aux, self.aux_dictionary)

    @property
    def nbytes(self) -> int:
        """Device bytes (values + validity) — the unit of memory accounting
        shared by the HBM pool (exec/memory.py) and scan caches."""
        n = int(getattr(self.values, "nbytes", 0) or 0)
        for a in (self.valid, self.lengths, self.aux):
            if a is not None:
                n += int(getattr(a, "nbytes", 0) or 0)
        return n

    @classmethod
    def from_numpy(cls, data: np.ndarray, typ: T.Type,
                   valid: Optional[np.ndarray] = None,
                   dictionary: Optional[Dictionary] = None) -> "Column":
        if T.is_string(typ) and dictionary is None:
            dictionary, codes = Dictionary.build(data)
            data = codes
        arr = jnp.asarray(np.asarray(data, dtype=T.to_numpy_dtype(typ)))
        v = None if valid is None else jnp.asarray(valid, dtype=jnp.bool_)
        return cls(arr, v, typ, dictionary)

    def to_numpy(self, num_rows: Optional[int] = None) -> np.ndarray:
        """Decode back to host values (python objects for strings/nulls).

        Slices on DEVICE before transfer: pages have large static capacities
        (scan pages are table-sized), and fetching the full padded array over
        a remote-TPU link costs capacity/num_rows times the useful bytes."""
        n = self.capacity if num_rows is None else int(num_rows)
        vals = np.asarray(self.values[:n])
        if self.dictionary is not None:
            out = self.dictionary.decode(vals)
        else:
            out = vals.astype(object)
        if self.valid is not None:
            mask = ~np.asarray(self.valid[:n])
            out = out.copy()
            out[mask] = None
        return out


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Page:
    """Horizontal batch of Columns + dynamic row count.

    Reference: spi/Page.java:33. `num_rows` may be a traced scalar under jit;
    `capacity` (static) is the shared array length of all columns.
    """

    columns: Tuple[Column, ...]
    num_rows: jnp.ndarray  # int32 scalar (python int ok outside jit)

    def tree_flatten(self):
        return (tuple(self.columns), self.num_rows), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        columns, num_rows = children
        return cls(tuple(columns), num_rows)

    @property
    def capacity(self) -> int:
        return self.columns[0].capacity if self.columns else 0

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, i: int) -> Column:
        return self.columns[i]

    def row_mask(self) -> jnp.ndarray:
        """Mask of live rows ([0, num_rows))."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.num_rows

    def append_column(self, col: Column) -> "Page":
        return Page(self.columns + (col,), self.num_rows)

    def select_columns(self, indices: Sequence[int]) -> "Page":
        return Page(tuple(self.columns[i] for i in indices), self.num_rows)

    def filter(self, mask: jnp.ndarray) -> "Page":
        """Compact rows where mask is true (Page.getPositions analog).

        jit-safe: output keeps this page's capacity; selected rows move to
        the front, num_rows becomes the selected count.

        Implementation: ONE stable sort on the drop-flag with every
        values/validity array as payload. On TPU this is ~8x faster than
        nonzero+gather and ~20x faster than cumsum scatters (measured at
        8M rows) — the sort engine is the fast path for data movement.
        """
        mask = mask & self.row_mask()
        count = jnp.sum(mask).astype(jnp.int32)
        if not self.columns:
            return Page((), count)
        payload = []
        has_list = any(c.lengths is not None for c in self.columns)
        for c in self.columns:
            if c.lengths is None:
                payload.append(c.values)
            if c.valid is not None:
                payload.append(c.valid)
        # list columns (2-D element planes) can't ride the multi-operand
        # sort; carry a permutation instead and gather them after
        perm = None
        if has_list:
            payload.append(jnp.arange(self.capacity, dtype=jnp.int32))
        out = jax.lax.sort([~mask] + payload, num_keys=1, is_stable=True)
        it = iter(out[1:])
        cols = []
        scalar_parts = []
        for c in self.columns:
            values = next(it) if c.lengths is None else None
            valid = next(it) if c.valid is not None else None
            scalar_parts.append((values, valid))
        if has_list:
            perm = out[-1]
        for c, (values, valid) in zip(self.columns, scalar_parts):
            if c.lengths is None:
                cols.append(Column(values, valid, c.type, c.dictionary))
            else:
                g = c.gather(perm)
                cols.append(Column(g.values, valid, c.type, c.dictionary,
                                   g.lengths, g.aux, g.aux_dictionary))
        return Page(tuple(cols), count)

    def gather(self, indices: jnp.ndarray, count) -> "Page":
        cols = tuple(c.gather(indices) for c in self.columns)
        return Page(cols, jnp.asarray(count, dtype=jnp.int32))

    def shrink_to(self, capacity: int) -> "Page":
        """Drop padding: slice every column to a smaller static capacity.

        Live rows are always a prefix (row_mask is `arange < num_rows`), so
        this is a pure O(capacity) device slice. Host-side only: the caller
        must know num_rows <= capacity (e.g. after a batched count fetch).
        Blocking operators shrink oversized intermediates so sorts/builds
        run at live size instead of scan-page capacity."""
        if capacity >= self.capacity:
            return self
        cols = tuple(
            Column(c.values[:capacity],
                   None if c.valid is None else c.valid[:capacity],
                   c.type, c.dictionary,
                   None if c.lengths is None else c.lengths[:capacity],
                   None if c.aux is None else c.aux[:capacity],
                   c.aux_dictionary)
            for c in self.columns)
        return Page(cols, self.num_rows)

    def pad_to(self, capacity: int) -> "Page":
        """Grow capacity (static) without changing live rows."""
        if capacity < self.capacity:
            raise ValueError("pad_to cannot shrink")
        if capacity == self.capacity:
            return self
        extra = capacity - self.capacity
        cols = []
        for c in self.columns:
            values = jnp.concatenate(
                [c.values, jnp.zeros((extra,), dtype=c.values.dtype)])
            valid = None
            if c.valid is not None:
                valid = jnp.concatenate(
                    [c.valid, jnp.zeros((extra,), dtype=jnp.bool_)])
            cols.append(Column(values, valid, c.type, c.dictionary))
        return Page(tuple(cols), self.num_rows)

    @classmethod
    def from_numpy(cls, arrays: Sequence[np.ndarray], typs: Sequence[T.Type],
                   valids: Optional[Sequence[Optional[np.ndarray]]] = None,
                   dictionaries: Optional[Sequence[Optional[Dictionary]]] = None,
                   ) -> "Page":
        n = len(arrays[0]) if arrays else 0
        valids = valids or [None] * len(arrays)
        dictionaries = dictionaries or [None] * len(arrays)
        cols = tuple(
            Column.from_numpy(a, t, v, d)
            for a, t, v, d in zip(arrays, typs, valids, dictionaries))
        return cls(cols, jnp.asarray(n, dtype=jnp.int32))

    def to_host(self, num_rows: Optional[int] = None) -> list:
        """All columns as decoded host arrays in ONE batched transfer.
        List (ARRAY/MAP) columns decode to python lists / dicts per row."""
        n = int(self.num_rows) if num_rows is None else num_rows
        fetch = []
        for c in self.columns:
            fetch.append((c.values[:n],
                          c.valid[:n] if c.valid is not None else None,
                          c.lengths[:n] if c.lengths is not None else None,
                          c.aux[:n] if c.aux is not None else None))
        host = jax.device_get(fetch)
        out = []
        for c, (vals, valid, lengths, aux) in zip(self.columns, host):
            if lengths is not None:
                rows = np.empty(n, dtype=object)
                for i in range(n):
                    ln = int(lengths[i])
                    elems = vals[i, :ln]
                    if c.dictionary is not None:
                        elems = c.dictionary.decode(elems)
                    if aux is not None:
                        avals = aux[i, :ln]
                        if c.aux_dictionary is not None:
                            avals = c.aux_dictionary.decode(avals)
                            avals = avals.tolist()
                        else:
                            avals = avals.tolist()
                        rows[i] = dict(zip(elems.tolist(), avals))
                    else:
                        rows[i] = list(elems.tolist())
                decoded = rows
            elif c.dictionary is not None:
                decoded = c.dictionary.decode(vals)
            else:
                decoded = vals.astype(object)
            if valid is not None:
                decoded = decoded.copy()
                decoded[~valid] = None
            out.append(decoded)
        return out

    def to_pylist(self) -> list:
        """Rows as python tuples (client-result materialization)."""
        n = int(self.num_rows)
        cols = self.to_host(n)
        return [tuple(col[i] for col in cols) for i in range(n)]


def union_dictionaries(dicts: Sequence[Dictionary]
                       ) -> Tuple[Dictionary, list]:
    """Rebase N dictionaries onto one union pool.

    Returns (union_dictionary, [int32 device remap array per input dict]):
    new_code = remap[i][old_code]. Host-side, static — callers cache per
    dictionary identity (DictionaryBlock 'compact to shared pool' analog)."""
    union = Dictionary(np.unique(np.concatenate([d.values for d in dicts])))
    remaps = [jnp.asarray(np.searchsorted(union.values, d.values)
                          .astype(np.int32)) for d in dicts]
    return union, remaps


def concat_pages(pages: Sequence[Page]) -> Page:
    """Host-side page concatenation (not jit-safe; used at stage boundaries).

    Transfer discipline for remote devices (~100ms per round trip through a
    TPU tunnel): ONE batched device_get for all row counts, then ONE for
    every column slice of every page — never a fetch per column. Slices are
    taken on device so only live rows cross the wire, not padded capacity.
    """
    if not pages:
        raise ValueError("no pages")
    if len(pages) == 1:
        return pages[0]
    ncols = pages[0].num_columns
    counts = [int(c) for c in jax.device_get([p.num_rows for p in pages])]
    total = sum(counts)
    for ci in range(ncols):
        ref = pages[0].column(ci)
        if any(p.column(ci).dictionary != ref.dictionary for p in pages):
            raise ValueError(
                f"column {ci}: pages use different dictionaries; re-encode "
                "to a shared dictionary before concatenating")
    needs_valid = [any(p.column(ci).valid is not None for p in pages)
                   for ci in range(ncols)]
    fetch = []
    for p, c in zip(pages, counts):
        for ci in range(ncols):
            col = p.column(ci)
            fetch.append(col.values[:c])
            if needs_valid[ci]:
                fetch.append(col.valid_mask()[:c])
    host = jax.device_get(fetch)
    it = iter(host)
    vparts: list = [[] for _ in range(ncols)]
    nparts: list = [[] for _ in range(ncols)]
    for p, c in zip(pages, counts):
        for ci in range(ncols):
            vparts[ci].append(next(it))
            if needs_valid[ci]:
                nparts[ci].append(next(it))
    cols = []
    for ci in range(ncols):
        ref = pages[0].column(ci)
        values = jnp.asarray(np.concatenate(vparts[ci])) if total \
            else ref.values[:0]
        valid = None
        if needs_valid[ci]:
            valid = jnp.asarray(np.concatenate(nparts[ci])) if total \
                else ref.valid_mask()[:0]
        cols.append(Column(values, valid, ref.type, ref.dictionary))
    return Page(tuple(cols), jnp.asarray(total, dtype=jnp.int32))


def device_concat(pages: Sequence[Page]) -> Page:
    """Concatenate pages ON DEVICE into one page of capacity sum(capacities).

    jit-safe (traced num_rows; static capacities): each page's FULL-capacity
    column is written with lax.dynamic_update_slice at the running live
    offset, in page order — page i+1's write starts where page i's live rows
    end, so it overwrites page i's padding tail; whatever garbage the last
    page leaves beyond the total live count is ordinary output padding
    (row_mask never reads it). Pure HBM-bandwidth copies — no host round
    trip (concat_pages bounces every live row through the host, ~100ms+ on
    a remote-tunnel device) and no sort pass.

    All pages must share column types/dictionaries (caller contract, same
    as concat_pages)."""
    if not pages:
        raise ValueError("no pages")
    if len(pages) == 1:
        return pages[0]
    ncols = pages[0].num_columns
    for ci in range(ncols):
        ref = pages[0].column(ci)
        if any(p.column(ci).dictionary != ref.dictionary for p in pages):
            raise ValueError(
                f"column {ci}: pages use different dictionaries; re-encode "
                "to a shared dictionary before concatenating")
    out_cap = sum(p.capacity for p in pages)
    counts = [p.num_rows.astype(jnp.int64) for p in pages]
    offs = []
    off = jnp.int64(0)
    for c in counts:
        offs.append(off)
        off = off + c
    total = off
    needs_valid = [any(p.column(ci).valid is not None for p in pages)
                   for ci in range(ncols)]
    cols = []
    for ci in range(ncols):
        ref = pages[0].column(ci)
        if ref.lengths is not None:
            # list columns: pad element planes to the widest page's L
            lmax = max(p.column(ci).values.shape[1] for p in pages)

            def plane(get):
                out2 = jnp.zeros((out_cap, lmax), dtype=get(ref).dtype)
                for p, o in zip(pages, offs):
                    a = get(p.column(ci))
                    if a.shape[1] < lmax:
                        a = jnp.pad(a, ((0, 0), (0, lmax - a.shape[1])))
                    out2 = jax.lax.dynamic_update_slice(out2, a, (o, 0))
                return out2
            values2 = plane(lambda c: c.values)
            aux2 = plane(lambda c: c.aux) if ref.aux is not None else None
            lens = jnp.zeros(out_cap, dtype=jnp.int32)
            for p, o in zip(pages, offs):
                lens = jax.lax.dynamic_update_slice(
                    lens, p.column(ci).lengths, (o,))
            valid = None
            if needs_valid[ci]:
                valid = jnp.zeros(out_cap, dtype=jnp.bool_)
                for p, o in zip(pages, offs):
                    valid = jax.lax.dynamic_update_slice(
                        valid, p.column(ci).valid_mask(), (o,))
            cols.append(Column(values2, valid, ref.type, ref.dictionary,
                               lens, aux2, ref.aux_dictionary))
            continue
        out = jnp.zeros(out_cap, dtype=ref.values.dtype)
        for p, o in zip(pages, offs):
            out = jax.lax.dynamic_update_slice(out, p.column(ci).values,
                                               (o,))
        valid = None
        if needs_valid[ci]:
            valid = jnp.zeros(out_cap, dtype=jnp.bool_)
            for p, o in zip(pages, offs):
                valid = jax.lax.dynamic_update_slice(
                    valid, p.column(ci).valid_mask(), (o,))
        cols.append(Column(out, valid, ref.type, ref.dictionary))
    return Page(tuple(cols), total.astype(jnp.int32))
