"""Serving-tier caches: result sets and table-scan pages.

Reference parity: the reference engine has no built-in result cache (it
fronts one with external layers; Presto forks ship a coordinator result
cache keyed on the canonical statement), and its connectors implement
scan caching individually (Hive/Alluxio). Here both live in the engine,
keyed on the SAME statement fingerprints the plan cache uses
(exec/plan_cache.py), and evicted through the plan cache's invalidation
hooks — one DDL/INSERT drops the plan, the cached result sets, and the
staged scan pages in a single call, so a stale answer is structurally
impossible rather than merely unlikely.

ResultSetCache: fully-materialized query answers. Key = the runner's
plan-cache key (canonical literal-free fingerprint + masked literal
values + catalog/schema/current_date + parameter types + plan
properties) plus the BOUND parameter values — a prepared statement's
plan is value-free but its answer is not. A hit returns rows with zero
planning, zero compiles, and zero operator execution. Entries record the
tables their plan referenced; `invalidate(table)` drops every entry
touching the table, and `put` carries the generation read before
execution so a result computed against pre-change data can never land
after the invalidation that should have dropped it (the same race guard
as PlanCache.put).

ScanCache: raw connector pages staged on device, keyed on (table,
columns, page capacity). Downstream filters/projections are pending
chain ops applied per query, so raw pages are reusable by ANY query
over the same columns — a warm scan skips the host->device staging that
dominates small-table latency. Byte-budgeted LRU (pages pin device
memory); invalidated per table like the result cache.

Both caches are per-runner (they hold handles/pages resolved against
that runner's catalogs), shared with `for_query()` clones under a lock
— the server's executor pool warms one of each.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import weakref
from typing import Any, Dict, FrozenSet, Hashable, List, Optional, Tuple

# the put-generation race discipline is single-sourced with the plan
# cache (the base of the invalidation fan-out): one mixin, three caches
from trino_tpu.exec.plan_cache import _GenerationGuard  # noqa: F401

TableKey = Tuple[str, str, str]   # (catalog, schema, table)

# process-lifetime counters across every runner's caches (obs/metrics.py
# exports these; system.runtime.caches scans them)
_RESULT_STATS = {"hits": 0, "misses": 0, "evictions": 0,
                 "invalidations": 0}
_SCAN_STATS = {"hits": 0, "misses": 0, "evictions": 0, "invalidations": 0}
_STATS_LOCK = threading.Lock()
_RESULT_INSTANCES: "weakref.WeakSet[ResultSetCache]" = weakref.WeakSet()
_SCAN_INSTANCES: "weakref.WeakSet[ScanCache]" = weakref.WeakSet()

DEFAULT_RESULT_MAX_ENTRIES = 128
DEFAULT_SCAN_BUDGET_BYTES = 512 << 20

# functions whose value depends on more than their arguments: a result
# containing one must be recomputed per execution (current_date is fine —
# it is part of the statement key via session.start_date)
_NONDETERMINISTIC_FUNCTIONS = frozenset({
    "random", "rand", "uuid", "shuffle", "now", "current_timestamp",
    "localtimestamp", "current_time", "localtime"})


def statement_is_cacheable(stmt) -> bool:
    """True when a statement's answer is a pure function of its text and
    the tables it reads: no nondeterministic function calls anywhere in
    the AST. Table-level concerns (system catalog, referenced-table
    invalidation) are handled by the caller from the executed plan."""
    from trino_tpu.sql import tree as t

    def walk(x) -> bool:
        if isinstance(x, t.FunctionCall):
            if x.name.suffix.lower() in _NONDETERMINISTIC_FUNCTIONS:
                return False
        if dataclasses.is_dataclass(x) and isinstance(x, t.Node):
            return all(walk(getattr(x, f.name))
                       for f in dataclasses.fields(x))
        if isinstance(x, (tuple, list)):
            return all(walk(item) for item in x)
        return True
    return walk(stmt)


def _count(stats: Dict[str, int], name: str, n: int = 1) -> None:
    with _STATS_LOCK:
        stats[name] += n


@dataclasses.dataclass
class CachedResult:
    """One materialized answer: what a cache-hit EXECUTE returns without
    touching the planner or the device."""

    column_names: Tuple[str, ...]
    column_types: Tuple[Any, ...]
    rows: Tuple[Tuple[Any, ...], ...]
    row_count: int
    output_bytes: int               # live-row device bytes of the answer
    tables: FrozenSet[TableKey]     # referenced tables, for invalidation


class ResultSetCache(_GenerationGuard):
    """LRU of materialized results with table-keyed invalidation and the
    put-generation race guard (see module docstring)."""

    def __init__(self, max_entries: int = DEFAULT_RESULT_MAX_ENTRIES):
        self._lock = threading.RLock()
        self._entries: "collections.OrderedDict[Hashable, CachedResult]" \
            = collections.OrderedDict()
        self.max_entries = max_entries
        self._init_generations()
        _RESULT_INSTANCES.add(self)

    def get(self, key: Hashable,
            count_miss: bool = True) -> Optional[CachedResult]:
        """`count_miss=False` is the server's POST-time probe: a probe
        miss falls through to the execute path, which counts the miss
        itself — counting both would double every dispatched query."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                if count_miss:
                    _count(_RESULT_STATS, "misses")
                return None
            self._entries.move_to_end(key)
            _count(_RESULT_STATS, "hits")
            return entry

    def put(self, key: Hashable, entry: CachedResult,
            gen: Optional[int] = None) -> bool:
        if self.max_entries <= 0:
            return False
        with self._lock:
            if self._stale_locked(entry.tables, gen):
                return False
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                _count(_RESULT_STATS, "evictions")
            return True

    def resize(self, max_entries: int) -> None:
        with self._lock:
            self.max_entries = max_entries
            while len(self._entries) > max(self.max_entries, 0):
                self._entries.popitem(last=False)
                _count(_RESULT_STATS, "evictions")

    def invalidate(self, table: TableKey) -> int:
        with self._lock:
            self._bump_generation_locked(table)
            stale = [k for k, e in self._entries.items()
                     if table in e.tables]
            for k in stale:
                del self._entries[k]
        if stale:
            _count(_RESULT_STATS, "invalidations", len(stale))
        return len(stale)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class ScanCache(_GenerationGuard):
    """Byte-budgeted LRU of raw staged scan pages, keyed on (table,
    column identities, page capacity)."""

    def __init__(self, budget_bytes: int = DEFAULT_SCAN_BUDGET_BYTES):
        self._lock = threading.RLock()
        # key -> (pages, nbytes); key[0] is the TableKey, for invalidation
        self._entries: "collections.OrderedDict[Hashable, tuple]" = \
            collections.OrderedDict()
        self.budget_bytes = budget_bytes
        self.resident_bytes = 0
        self._init_generations()
        _SCAN_INSTANCES.add(self)

    def get(self, key: Hashable) -> Optional[List]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                _count(_SCAN_STATS, "misses")
                return None
            self._entries.move_to_end(key)
            _count(_SCAN_STATS, "hits")
            return entry[0]

    def put(self, key: Hashable, pages: List,
            gen: Optional[int] = None) -> bool:
        from trino_tpu.exec.memory import page_bytes
        nbytes = sum(page_bytes(p) for p in pages)
        if nbytes > self.budget_bytes:
            return False    # one oversized scan must not evict everything
        with self._lock:
            if self._stale_locked((key[0],), gen):
                return False    # the table changed while this scan ran
            old = self._entries.pop(key, None)
            if old is not None:
                self.resident_bytes -= old[1]
            self._entries[key] = (list(pages), nbytes)
            self.resident_bytes += nbytes
            while self.resident_bytes > self.budget_bytes and self._entries:
                _, (_, freed) = self._entries.popitem(last=False)
                self.resident_bytes -= freed
                _count(_SCAN_STATS, "evictions")
            return True

    def invalidate(self, table: TableKey) -> int:
        with self._lock:
            self._bump_generation_locked(table)
            stale = [k for k in self._entries if k[0] == table]
            for k in stale:
                _, nbytes = self._entries.pop(k)
                self.resident_bytes -= nbytes
        if stale:
            _count(_SCAN_STATS, "invalidations", len(stale))
        return len(stale)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.resident_bytes = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


def result_cache_stats() -> Dict[str, int]:
    """Process-lifetime counters + resident entries across live caches
    (obs/metrics.py gauges + system.runtime.caches)."""
    with _STATS_LOCK:
        out = dict(_RESULT_STATS)
    caches = list(_RESULT_INSTANCES)
    out["entries"] = sum(len(c) for c in caches)
    out["max_entries"] = sum(c.max_entries for c in caches)
    return out


def scan_cache_stats() -> Dict[str, int]:
    with _STATS_LOCK:
        out = dict(_SCAN_STATS)
    caches = list(_SCAN_INSTANCES)
    out["entries"] = sum(len(c) for c in caches)
    out["bytes"] = sum(c.resident_bytes for c in caches)
    return out
