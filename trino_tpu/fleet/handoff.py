"""SCM_RIGHTS listener-socket handoff between engine generations.

A PLANNED engine restart must not drop queries, and the dispatch port is
the only resource two engine processes cannot share by re-binding: while
the old engine still listens, a plain bind() fails, and closing first
opens a refused-connection window. The fix is the classic one (nginx,
HAProxy, Envoy hot restart): pass the LISTENING file descriptor itself
to the replacement over a unix stream socket via SCM_RIGHTS ancillary
data. The kernel accept queue rides along with the fd — connections
that arrive while neither process is accepting simply wait in the
backlog, so the swap is zero-drop by construction:

    old engine                         new engine
    ----------                         ----------
    dup(listener fd)
    TrinoServer.stop()    # full drain: in-flight queries + streams
    connect(handoff.sock)
    sendmsg(fd)  ------------------->  recvmsg(fd)
    exit                               TrinoServer(listen_fd=fd).start()

The protocol is deliberately sequential — the old engine finishes its
drain BEFORE the fd moves, so a GET for an in-flight old-engine query
can never land on the replacement (which would 404 it). POSTs that race
the drain are answered SERVER_SHUTTING_DOWN, which the workers retry
against the replacement (the engine rejected them before execution, so
the retry is safe).
"""

from __future__ import annotations

import array
import json
import os
import socket
import struct
from typing import Dict, List, Optional, Tuple

# one u32 length prefix for the JSON metadata that rides with the fds
_LEN = struct.Struct("!I")
MAX_META = 1 << 20


def send_fds(sock: socket.socket, fds: List[int],
             meta: Optional[Dict] = None) -> None:
    """Send `fds` + a JSON metadata dict over a connected unix stream
    socket in ONE sendmsg (ancillary data must accompany at least one
    byte of real data; the length-prefixed meta is that byte)."""
    payload = json.dumps(meta or {}).encode("utf-8")
    if len(payload) > MAX_META:
        raise ValueError("handoff metadata too large")
    buf = _LEN.pack(len(payload)) + payload
    anc = [(socket.SOL_SOCKET, socket.SCM_RIGHTS,
            array.array("i", [int(fd) for fd in fds]).tobytes())]
    sock.sendmsg([buf], anc)


def recv_fds(sock: socket.socket, max_fds: int = 4
             ) -> Tuple[List[int], Dict]:
    """Receive (fds, metadata) sent by `send_fds`. Raises ConnectionError
    if the peer closed without sending (a crashed offerer must not look
    like an empty handoff)."""
    space = socket.CMSG_SPACE(max_fds * array.array("i").itemsize)
    data, ancdata, flags, _ = sock.recvmsg(_LEN.size, space)
    if len(data) < _LEN.size:
        raise ConnectionError("handoff peer closed before sending")
    fds: List[int] = []
    for level, ctype, cdata in ancdata:
        if level == socket.SOL_SOCKET and ctype == socket.SCM_RIGHTS:
            arr = array.array("i")
            arr.frombytes(cdata[:len(cdata)
                                - (len(cdata) % arr.itemsize)])
            fds.extend(int(fd) for fd in arr)
    (nbytes,) = _LEN.unpack(data)
    if nbytes > MAX_META:
        for fd in fds:
            os.close(fd)
        raise ConnectionError("handoff metadata too large")
    payload = b""
    while len(payload) < nbytes:
        chunk = sock.recv(nbytes - len(payload))
        if not chunk:
            for fd in fds:
                os.close(fd)
            raise ConnectionError("handoff peer closed mid-metadata")
        payload += chunk
    meta = json.loads(payload.decode("utf-8")) if payload else {}
    return fds, meta


class HandoffListener:
    """The RECEIVING half, owned by the replacement engine: bind a unix
    stream socket at `path` (unlinking any stale one), then block in
    `accept_fds` until the old engine connects and offers its listener."""

    def __init__(self, path: str):
        self.path = path
        try:
            os.unlink(path)
        except OSError:
            pass
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(path)
        self._sock.listen(1)

    def accept_fds(self, timeout_s: float = 30.0,
                   max_fds: int = 4) -> Tuple[List[int], Dict]:
        self._sock.settimeout(timeout_s)
        conn, _ = self._sock.accept()
        try:
            conn.settimeout(timeout_s)
            return recv_fds(conn, max_fds)
        finally:
            conn.close()

    def close(self) -> None:
        try:
            self._sock.close()
        finally:
            try:
                os.unlink(self.path)
            except OSError:
                pass


def offer_fds(path: str, fds: List[int], meta: Optional[Dict] = None,
              timeout_s: float = 30.0) -> None:
    """The SENDING half, called by the draining engine: connect to the
    replacement's handoff socket and pass the listener fd(s)."""
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        sock.settimeout(timeout_s)
        sock.connect(path)
        send_fds(sock, fds, meta)
    finally:
        sock.close()
