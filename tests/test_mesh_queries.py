"""Multi-chip sharded execution on the 8-device CPU mesh.

The tier-1 proof of the co-scheduled mesh path (exec/mesh_exec.py):
q1/q3/q5/q9 execute sharded end-to-end — leaf scans one-shard-per-device,
joins/aggregations per shard, inter-fragment repartitioning as in-program
collectives — and must match BOTH the single-device engine and the sqlite
oracle, with the new exchange counters proving zero host-page staging
(every exchange 'fused', none 'staged'). Plus the skew-aware join path
and per-chip pool accounting.

Marker + subprocess wiring (pytest.ini `mesh`): these tests need an
8-device mesh. tests/conftest.py forces
XLA_FLAGS=--xla_force_host_platform_device_count=8 before jax
initializes, so under tier-1 they run inline; when collected into a
process whose backend came up with fewer devices (user XLA_FLAGS,
pre-initialized jax), the module re-runs ITSELF in a subprocess with the
forced 8-device CPU mesh instead of skipping — tier-1 exercises the
sharded path without a TPU either way.
"""

import os
import subprocess
import sys

import jax
import pytest

pytestmark = pytest.mark.mesh

_REQUIRED_DEVICES = 8


def _inline() -> bool:
    return len(jax.devices()) >= _REQUIRED_DEVICES or \
        bool(os.environ.get("TRINO_TPU_MESH_SUBPROC"))


if not _inline():
    _RESULT = {}

    def _subprocess_suite():
        """Run this module once in a subprocess with the forced 8-device
        CPU mesh; cache the result for every collected test."""
        if "rc" not in _RESULT:
            env = dict(os.environ)
            env["TRINO_TPU_MESH_SUBPROC"] = "1"
            env["JAX_PLATFORMS"] = "cpu"
            # replace (not append) any existing device-count flag: the
            # subprocess must come up with exactly the required mesh
            flags = [f for f in env.get("XLA_FLAGS", "").split()
                     if not f.startswith(
                         "--xla_force_host_platform_device_count")]
            flags.append("--xla_force_host_platform_device_count="
                         f"{_REQUIRED_DEVICES}")
            env["XLA_FLAGS"] = " ".join(flags)
            proc = subprocess.run(
                [sys.executable, "-m", "pytest", os.path.abspath(__file__),
                 "-q", "-p", "no:cacheprovider"],
                env=env, capture_output=True, text=True, timeout=1800,
                cwd=os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))))
            _RESULT["rc"] = proc.returncode
            _RESULT["tail"] = (proc.stdout[-4000:] + "\n"
                               + proc.stderr[-2000:])
        return _RESULT

    def test_mesh_suite_in_subprocess():
        got = _subprocess_suite()
        assert got["rc"] == 0, got["tail"]

else:
    import numpy as np

    from trino_tpu.exec import LocalQueryRunner
    from trino_tpu.exec.distributed import DistributedQueryRunner

    from oracle import assert_same, load_tpch_sqlite
    from tpch_sql import QUERIES

    MESH_QUERIES = ["q1", "q3", "q5", "q9"]

    @pytest.fixture(scope="module")
    def local():
        return LocalQueryRunner.tpch("tiny")

    @pytest.fixture(scope="module")
    def dist():
        return DistributedQueryRunner.tpch("tiny")

    @pytest.fixture(scope="module")
    def oracle():
        conn = load_tpch_sqlite(0.01)
        yield conn
        conn.close()

    @pytest.mark.parametrize("name", MESH_QUERIES)
    def test_mesh_sharded_end_to_end(local, dist, oracle, name):
        """q1/q3/q5/q9 sharded over 8 devices: row parity vs the
        single-device engine AND the sqlite oracle, with every
        inter-fragment exchange fused into the co-scheduled program
        (zero host-page exchanges — the acceptance criterion)."""
        engine_sql, oracle_sql, ordered = QUERIES[name]
        got = dist.execute(engine_sql)
        st = dist.last_query_stats
        assert st["mesh_devices"] == _REQUIRED_DEVICES, st
        assert st["exchanges_fused"] > 0, st
        assert st["exchanges_staged"] == 0, st
        assert st["exchange_rows"] > 0 and st["exchange_bytes"] > 0, st

        expect = local.execute(engine_sql)
        assert got.column_names == expect.column_names
        assert_same(got.rows, expect.rows, ordered)
        expected = oracle.execute(oracle_sql or engine_sql).fetchall()
        assert_same(got.rows, expected, ordered)

    def test_mesh_partitioned_join_fused(local, dist):
        """Forced PARTITIONED distribution: both join inputs repartition
        on the clause keys and the exchange pair fuses into the join
        program (the skew-aware pair when enabled)."""
        dist.execute("SET SESSION join_distribution_type = 'PARTITIONED'")
        try:
            sql = ("SELECT c_mktsegment, count(*), sum(o_totalprice) "
                   "FROM customer, orders WHERE c_custkey = o_custkey "
                   "GROUP BY c_mktsegment")
            got = dist.execute(sql)
            st = dist.last_query_stats
            assert st["exchanges_staged"] == 0, st
            assert st["exchanges_fused"] >= 3, st   # probe + build + agg
            expect = local.execute(sql)
            assert_same(got.rows, expect.rows, False)
        finally:
            dist.execute("RESET SESSION join_distribution_type")

    def test_mesh_skewed_key_join(local, dist):
        """Skewed-key join through the spread/replicate exchange pair:
        lineitem's l_orderkey distribution is skewed by construction of
        the filter (one hot orderkey family via small key space after
        modulo is not available, so force skew handling by shrinking the
        heavy-hitter threshold is implicit — correctness must hold with
        skew handling ON and OFF and results must be identical)."""
        dist.execute("SET SESSION join_distribution_type = 'PARTITIONED'")
        sql = ("SELECT l_linestatus, count(*) FROM lineitem, orders "
               "WHERE l_orderkey = o_orderkey GROUP BY l_linestatus")
        try:
            expect = local.execute(sql)
            got_skew = dist.execute(sql)
            st = dist.last_query_stats
            assert st["exchanges_staged"] == 0, st
            assert_same(got_skew.rows, expect.rows, False)
            dist.execute("SET SESSION skewed_exchange_enabled = false")
            got_plain = dist.execute(sql)
            assert_same(got_plain.rows, expect.rows, False)
            assert sorted(got_skew.rows) == sorted(got_plain.rows)
        finally:
            dist.execute("RESET SESSION skewed_exchange_enabled")
            dist.execute("RESET SESSION join_distribution_type")

    def test_mesh_group_by_strategy_by_ndv(dist):
        """CBO strategy selection ("Global Hash Tables Strike Back"):
        low-NDV GROUP BY gathers tiny partial states (global strategy, no
        all_to_all); high-NDV GROUP BY repartitions (partitioned
        strategy). Observable through the distributed plan text."""
        low = dist.execute(
            "EXPLAIN (TYPE DISTRIBUTED) SELECT l_returnflag, count(*) "
            "FROM lineitem GROUP BY l_returnflag").only_value()
        high = dist.execute(
            "EXPLAIN (TYPE DISTRIBUTED) SELECT l_orderkey, count(*) "
            "FROM lineitem GROUP BY l_orderkey").only_value()
        assert "gather" in low and "repartition" not in low, low
        assert "repartition" in high, high

    def test_mesh_per_chip_pool_accounting(dist):
        """Sharded staging attributes reservations per chip: after a
        mesh query, every device shows a nonzero peak and the node-pool
        per-device gauges surface in system.runtime.nodes."""
        from trino_tpu.exec.memory import NODE_POOL
        dist.execute("SELECT count(*) FROM lineitem")
        peaks = [NODE_POOL.device_peak.get(i, 0)
                 for i in range(_REQUIRED_DEVICES)]
        assert all(p > 0 for p in peaks), peaks
        rows = dist.execute(
            "SELECT node_id, pool_budget_source, device_peak_bytes "
            "FROM system.runtime.nodes").rows
        assert len(rows) == _REQUIRED_DEVICES
        assert all(r[1] in ("default", "measured") for r in rows)
        assert any(r[2] > 0 for r in rows), rows

    def test_mesh_query_info_carries_mesh_shape(dist):
        from trino_tpu.exec.query_tracker import TRACKER
        sql = "SELECT count(*) AS mesh_shape_probe FROM nation"
        dist.execute(sql)
        info = next(q for q in TRACKER.list() if q.query == sql)
        assert info.mesh == f"workers:{_REQUIRED_DEVICES}"
        assert info.stats["mesh_devices"] == _REQUIRED_DEVICES

    def test_mesh_fallback_still_correct(local, dist):
        """mesh_execution=false pins the dispatch-loop path; results
        must match and the exchange counters must read 'staged'."""
        sql = ("SELECT o_orderpriority, count(*) FROM orders "
               "GROUP BY o_orderpriority")
        dist.execute("SET SESSION mesh_execution = false")
        try:
            got = dist.execute(sql)
            st = dist.last_query_stats
            assert st["exchanges_fused"] == 0, st
            assert st["exchanges_staged"] > 0, st
            assert_same(got.rows, local.execute(sql).rows, False)
        finally:
            dist.execute("RESET SESSION mesh_execution")

    def test_mesh_table_cache_zero_staging(dist):
        """The lake-round mesh acceptance: a CTAS'd lake table's first
        mesh scan stages from the connector (and promotes the hot set);
        the REPEATED mesh scan serves shard slices straight from the
        HBM-resident columns — zero host->device staging bytes — while
        the program's exchanges stay fused."""
        dist.execute("CREATE TABLE lake.default.mesh_hot AS "
                     "SELECT * FROM orders")
        dist.execute("SET SESSION table_cache_enabled = true")
        dist.execute("SET SESSION table_cache_min_scans = 1")
        sql = ("SELECT o_orderstatus, count(*), sum(o_totalprice) "
               "FROM lake.default.mesh_hot GROUP BY o_orderstatus")
        try:
            first = dist.execute(sql)
            st1 = dist.last_query_stats
            assert st1["mesh_devices"] == _REQUIRED_DEVICES, st1
            assert st1["exchanges_fused"] > 0, st1
            assert st1["scan_staging_bytes"] > 0, st1
            second = dist.execute(sql)
            st2 = dist.last_query_stats
            assert st2["table_cache_hits"] >= 1, st2
            assert st2["scan_staging_bytes"] == 0, st2
            assert st2["exchanges_fused"] > 0, st2
            assert_same(second.rows, first.rows, False)
            expect = dist.execute(
                "SELECT o_orderstatus, count(*), sum(o_totalprice) "
                "FROM orders GROUP BY o_orderstatus")
            assert_same(second.rows, expect.rows, False)
        finally:
            dist.execute("RESET SESSION table_cache_enabled")
            dist.execute("DROP TABLE lake.default.mesh_hot")

    def test_mesh_operator_stats_parity(dist, oracle):
        """Round-13 acceptance: collect_operator_stats no longer forces
        mesh programs off the fused data plane. The instrumented q1 run
        keeps exchanges_staged == 0 with the SAME fused-exchange count
        as the plain run, stays oracle-correct, and emits program-level
        operator rows with cost-apportioned device walls for the
        co-scheduled child fragments."""
        engine_sql, oracle_sql, ordered = QUERIES["q1"]
        dist.execute(engine_sql)
        plain = dict(dist.last_query_stats)
        assert plain["exchanges_fused"] > 0, plain
        dist.execute("SET SESSION collect_operator_stats = true")
        try:
            got = dist.execute(engine_sql)
            st = dict(dist.last_query_stats)
        finally:
            dist.execute("RESET SESSION collect_operator_stats")
        # the data plane did not change: still fused, nothing staged
        assert st["exchanges_staged"] == 0, st
        assert st["exchanges_fused"] == plain["exchanges_fused"], \
            (plain["exchanges_fused"], st["exchanges_fused"])
        assert st["mesh_devices"] == _REQUIRED_DEVICES, st
        # program-level stats rows present: the mesh child fragment's
        # nodes (scan/partial agg) report cost-apportioned device walls
        ops = st.get("operators", [])
        assert ops, st
        names = {o["name"] for o in ops}
        assert "TableScanNode" in names, names
        assert st["device_time_ms"] > 0, st
        assert any(o["device_ms"] > 0 for o in ops), ops
        expected = oracle.execute(oracle_sql or engine_sql).fetchall()
        assert_same(got.rows, expected, ordered)
