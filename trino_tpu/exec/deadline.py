"""Query deadlines + cooperative cancellation.

Reference parity: QueryStateMachine's query_max_run_time /
query_max_execution_time enforcement (execution/QueryTracker.java
enforceTimeLimits:183 — run time counts from CREATE i.e. queueing,
execution time from the start of planning) and cancellation propagation
(QueryStateMachine.transitionToCanceled walking the stage tree). The
single-controller engine has no per-stage threads to interrupt, so both
collapse to ONE object threaded through the runner and checked
cooperatively at fragment and page-batch boundaries; a device program
already in flight finishes, but the query stops at the next boundary.

The cancel flag is a threading.Event because it IS crossed by threads: the
HTTP server's DELETE handler sets it while the executor thread runs the
query.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from trino_tpu.errors import QueryCanceledError, QueryTimeoutError

_UNITS = {"ns": 1e-9, "us": 1e-6, "ms": 1e-3, "s": 1.0,
          "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_duration(value) -> Optional[float]:
    """Trino Duration strings ('30s', '2m', '500ms') or bare numbers
    (seconds) -> seconds; None/''/0 -> no limit."""
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return float(value) if value > 0 else None
    text = str(value).strip().lower()
    if not text:
        return None
    for unit in sorted(_UNITS, key=len, reverse=True):
        if text.endswith(unit):
            num = text[: -len(unit)].strip()
            if num:
                return float(num) * _UNITS[unit] or None
    return float(text) or None


class CancelEvent(threading.Event):
    """A cancel-request token: threading.Event plus the monotonic time
    the FIRST cancel landed — the numerator of `preempt_latency_ms`
    (request -> unwind). Stamping lives HERE, in one place: callers
    (the server's DELETE handler, bench --preempt) call `cancel()`
    instead of hand-ordering a timestamp write before `set()`. Plain
    Events are still accepted everywhere a cancel_event is taken; they
    just degrade the latency stamp to first observation."""

    def __init__(self):
        super().__init__()
        self.cancelled_at: Optional[float] = None

    def cancel(self) -> None:
        if self.cancelled_at is None:
            self.cancelled_at = time.monotonic()
        self.set()


class QueryDeadline:
    """Wall-clock limits + cancel flag for one query."""

    def __init__(self, max_run_s: Optional[float] = None,
                 max_exec_s: Optional[float] = None,
                 queued_at: Optional[float] = None,
                 cancel_event: Optional[threading.Event] = None):
        now = time.monotonic()
        self._cancel = cancel_event or threading.Event()
        # when the FIRST cancel request landed (monotonic): the
        # preemption-latency numerator — cancel-request to unwind is the
        # slice-bounded wall the sliced executor promises (obs surfaces
        # it as `preempt_latency_ms`). DELETE handlers setting the
        # shared cancel_event directly are also covered: check() stamps
        # it on first observation if cancel() was bypassed.
        self.cancelled_at: Optional[float] = None
        self.queued_at = queued_at if queued_at is not None else now
        self.exec_started = now
        self.max_run_s = max_run_s
        self.max_exec_s = max_exec_s
        self._run_deadline = (self.queued_at + max_run_s
                              if max_run_s else None)
        self._exec_deadline = now + max_exec_s if max_exec_s else None

    @classmethod
    def from_session(cls, session, queued_at: Optional[float] = None,
                     wall_cap_s: Optional[float] = None,
                     cancel_event: Optional[threading.Event] = None
                     ) -> "QueryDeadline":
        """Session-property limits, optionally tightened by a server-side
        wall cap (the resource-group hard limit analog)."""
        max_run = parse_duration(session.get("query_max_run_time"))
        max_exec = parse_duration(session.get("query_max_execution_time"))
        if wall_cap_s is not None:
            max_run = (wall_cap_s if max_run is None
                       else min(max_run, wall_cap_s))
        return cls(max_run, max_exec, queued_at, cancel_event)

    def cancel(self) -> None:
        if self.cancelled_at is None:
            self.cancelled_at = time.monotonic()
        # stamp the Event too: the server's DELETE handler shares this
        # Event and sets it directly — whichever side cancels first, the
        # request time survives on the shared object
        if getattr(self._cancel, "cancelled_at", None) is None:
            self._cancel.cancelled_at = self.cancelled_at
        self._cancel.set()

    @property
    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def check(self) -> None:
        """Cooperative checkpoint: raises if canceled or past a limit."""
        if self._cancel.is_set():
            if self.cancelled_at is None:
                # event set externally (the server's DELETE handler owns
                # the Event and stamps `cancelled_at` on it); an unknown
                # external setter degrades to observation time
                self.cancelled_at = getattr(
                    self._cancel, "cancelled_at", None) or time.monotonic()
            raise QueryCanceledError("Query was canceled by user")
        now = time.monotonic()
        if self._run_deadline is not None and now > self._run_deadline:
            raise QueryTimeoutError(
                f"Query exceeded maximum run time of "
                f"{_fmt_s(self.max_run_s)}")
        if self._exec_deadline is not None and now > self._exec_deadline:
            raise QueryTimeoutError(
                f"Query exceeded maximum execution time of "
                f"{_fmt_s(self.max_exec_s)}")


def _fmt_s(seconds: Optional[float]) -> str:
    if seconds is None:
        return "?"
    if seconds >= 1.0 and seconds == int(seconds):
        return f"{int(seconds)}s"
    return f"{seconds * 1000:.0f}ms"
