"""The 99 TPC-DS benchmark queries against the engine + sqlite oracle.

Reference parity: testing/trino-benchto-benchmarks tpcds suite +
TpcdsQueryRunner — the full decision-support workload. Query text loads
from the reference checkout at runtime (spec material; see
tpcds_queries.py) — tests skip when it isn't present.

Three tiers:
- VERIFIED: engine rows == sqlite oracle rows (float-decimal schema,
  surrogate-key indexes) at SF0.01, multiset comparison.
- EXECUTES: runs through parse/plan/optimize/execute and returns without
  error; sqlite can't run the query (ROLLUP/GROUPING()/compound-set
  parens/stddev-shape) or the LIMIT tie-break diverges — still asserted
  not to regress.
- KNOWN_FAILING: tracked gaps, asserted to fail (so a fix shows up as an
  xpass to promote).
"""

import pytest

import tpcds_queries
from trino_tpu.exec import LocalQueryRunner

pytestmark = pytest.mark.skipif(
    not tpcds_queries.available(),
    reason="reference TPC-DS query resources not present")

# engine == oracle at SF0.01 (generated list; see NOTES_r05.md)
VERIFIED = [
    "q01", "q03", "q04", "q06", "q07", "q09", "q10", "q11", "q12", "q13",
    "q15", "q16", "q17", "q19", "q20", "q21", "q23", "q24", "q25", "q26",
    "q28", "q29", "q30", "q31", "q32", "q33", "q34", "q35", "q37", "q38",
    "q39", "q40", "q41", "q42", "q43", "q44", "q45", "q46", "q47", "q48",
    "q49",
    "q50", "q51", "q52", "q53", "q54", "q55", "q56", "q57", "q58", "q59",
    "q60", "q61", "q62", "q63", "q64", "q65", "q68", "q69", "q71", "q72",
    "q73", "q74", "q75", "q76", "q78", "q79", "q81", "q82", "q83", "q84",
    "q85", "q88", "q89", "q91", "q92", "q93", "q94", "q95", "q96", "q97",
    "q98", "q99",
]

# engine executes; oracle can't run the shape (sqlite: no ROLLUP/
# GROUPING(), no parenthesized compound-set operands) or the comparison
# hits a documented representation deviation: q66 sums per-row decimal
# divisions, which Trino (and this engine) round to the decimal scale
# per row while the float oracle keeps full precision; q90's decimal
# division by zero is garbage where Trino errors
EXECUTES = [
    "q02", "q05", "q08", "q14", "q18", "q22", "q27", "q36", "q66", "q67",
    "q70", "q77", "q80", "q86", "q87", "q90",
]

# tracked gaps (none currently — every query executes; promote to
# VERIFIED/EXECUTES when adding entries back)
KNOWN_FAILING = {}


# the full 99-query sweep takes ~15 min on the 1-core host; default CI
# runs a representative sample across the join/agg/window/set-op shapes,
# TRINO_TPU_TPCDS_FULL=1 runs everything (what NOTES_r05 reports)
import os

_FULL = os.environ.get("TRINO_TPU_TPCDS_FULL", "0") == "1"
_SAMPLE = ["q03", "q07", "q10", "q23", "q31", "q38", "q49", "q51", "q54",
           "q64", "q72", "q74", "q88", "q93", "q99"]
_VERIFIED_RUN = VERIFIED if _FULL else \
    [q for q in _SAMPLE if q in VERIFIED]
_EXECUTES_RUN = EXECUTES if _FULL else ["q27", "q36", "q86", "q90"]


@pytest.fixture(scope="module")
def runner():
    r = LocalQueryRunner.tpch("tiny")
    r.execute("USE tpcds.tiny")
    return r


@pytest.fixture(scope="module")
def queries():
    return tpcds_queries.load_queries()


@pytest.fixture(scope="module")
def oracle():
    from oracle import load_tpcds_sqlite_float
    conn = load_tpcds_sqlite_float(0.01)
    yield conn
    conn.close()


@pytest.mark.parametrize("name", _VERIFIED_RUN)
def test_verified_vs_oracle(runner, queries, oracle, name):
    from oracle import assert_same
    engine = runner.execute(queries[name]).rows
    got = oracle.execute(
        tpcds_queries.to_oracle_sql(queries[name])).fetchall()
    assert_same(engine, got, ordered=False)


@pytest.mark.parametrize("name", _EXECUTES_RUN)
def test_executes(runner, queries, name):
    runner.execute(queries[name])   # must not raise


@pytest.mark.parametrize("name", sorted(KNOWN_FAILING))
def test_known_failing(runner, queries, name):
    with pytest.raises(Exception):
        runner.execute(queries[name])
