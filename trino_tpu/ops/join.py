"""Hash join as sorted-build + binary-search probe + cumsum expansion.

Reference parity: operator/join/ (HashBuilderOperator.java:59, PagesHash.java,
LookupJoinOperator.java:36, HashSemiJoinOperator, NestedLoopJoinOperator).

TPU design: open-addressing tables probe with data-dependent loops — a poor
VPU fit. Instead:
  build:  sort build rows by join key (lax.sort)                O(n log n)
  probe:  lower/upper bound via vectorized searchsorted         O(m log n)
  expand: match counts -> cumsum offsets -> one gather per side O(out)
This is exact for duplicate keys (a probe row emits hi-lo rows) and fully
static-shape: the output page has a planner-chosen capacity; the operator also
returns the true match total so the executor can detect overflow and re-run
at a larger capacity bucket (SURVEY §7 hard part 1).

Composite keys collapse to one u64 via a mixing hash and every join type
verifies the real key columns post-expansion: INNER/LEFT/FULL filter
collision slots exactly (LEFT/FULL additionally rescue probe rows whose
every candidate was a collision as null-extension rows), and SEMI/ANTI/MARK
re-check candidates and scatter the verdict back per probe row. SQL
semantics: NULL join keys never match (including NULL = NULL); LEFT/FULL
rows without matches emit once with the other side NULL.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from trino_tpu import types as T
from trino_tpu.page import Column, Page


class JoinType:
    INNER = "inner"
    LEFT = "left"          # probe side preserved
    SEMI = "semi"          # probe rows with >=1 match (IN / EXISTS)
    ANTI = "anti"          # probe rows with 0 matches (NOT IN w/o nulls)
    FULL = "full"          # both sides preserved (executor accumulates the
                           # build-matched mask and emits unmatched build
                           # rows via unmatched_build_page)
    MARK = "mark"          # all probe rows + bool match channel
    # (HashSemiJoinOperator appends the semi-join result as a column;
    # used when the match symbol escapes into projections/other filters)


_MIX = jnp.uint64(0x9E3779B97F4A7C15)


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer — the PagesHash hash-combining analog."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> 27)) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> 31)


def _key_u64(page: Page, channels: Sequence[int]) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(key, key_is_null): single u64 key; composite keys mix-hashed."""
    cols = [page.column(ch) for ch in channels]
    null = jnp.zeros(page.capacity, dtype=jnp.bool_)
    for c in cols:
        if c.valid is not None:
            null = null | ~c.valid
    def to_u64(raw):
        if raw.dtype == jnp.bool_:
            return raw.astype(jnp.uint64)
        if jnp.issubdtype(raw.dtype, jnp.floating):
            # canonicalize -0.0 -> +0.0 so SQL-equal doubles get equal bits
            return jax.lax.bitcast_convert_type(
                raw.astype(jnp.float64) + 0.0, jnp.uint64)
        return raw.astype(jnp.uint64)

    if len(cols) == 1:
        return to_u64(cols[0].values), null
    acc = jnp.zeros(page.capacity, dtype=jnp.uint64)
    for c in cols:
        k = to_u64(c.values)
        acc = _mix64(acc ^ _mix64(k) ^ (acc * _MIX))
    return acc, null


def _mark_page(probe: Page, matched: jnp.ndarray, pnull: jnp.ndarray,
               n_build_rows: jnp.ndarray,
               build_has_null: jnp.ndarray) -> Page:
    """Append the semi-join verdict as a boolean channel.

    Full IN-subquery 3VL: TRUE on a key match; NULL when the probe key is
    NULL against a non-empty build side, OR when there is no match but the
    build side contains a NULL key; FALSE otherwise (incl. any probe against
    an empty build side)."""
    value = matched & ~pnull
    definite = jnp.where(pnull, n_build_rows == 0, ~build_has_null)
    valid = matched | definite
    mark = Column(value, valid, T.BOOLEAN, None)
    return Page(tuple(probe.columns) + (mark,), probe.num_rows)


def prepare_build(build_keys: Sequence[int]):
    """Build-phase kernel: sort the build side ONCE into a LookupSource-like
    pytree consumed by every probe-page call (reference:
    operator/join/LookupSourceFactory — the build runs once per join, not
    once per probe page). Returns prep(build_page) -> prepared tuple."""
    build_keys = tuple(build_keys)

    def prep(build: Page):
        bkey, bnull = _key_u64(build, build_keys)
        # dead/null build rows: mask their key to u64::MAX and sort by
        # (key, dead) — keeps the key array globally sorted for
        # searchsorted while live rows occupy the prefix [0, n_live)
        b_dead = ~build.row_mask() | bnull
        u64max = jnp.uint64(0xFFFFFFFFFFFFFFFF)
        bkey_masked = jnp.where(b_dead, u64max, bkey)
        sort_ops = jax.lax.sort(
            [bkey_masked, b_dead,
             jnp.arange(build.capacity, dtype=jnp.int32)],
            num_keys=2)
        bkey_s, b_dead_s, bperm = sort_ops
        n_live_build = jnp.sum(~b_dead_s).astype(jnp.int32)
        live_b = build.row_mask()
        n_build_rows = jnp.sum(live_b).astype(jnp.int32)
        build_has_null = jnp.any(bnull & live_b)
        # per-position run length of equal keys: lets the probe derive its
        # upper bound from the lower bound (hi = lo + run_len[lo]) with no
        # second searchsorted — each probe-side searchsorted costs a full
        # sort-engine pass at scale
        n = build.capacity
        idx = jnp.arange(n, dtype=jnp.int32)
        boundary = (bkey_s != jnp.roll(bkey_s, 1)).at[0].set(True)
        run_start = jax.lax.cummax(jnp.where(boundary, idx, 0))
        nxt = jnp.where(boundary, idx, n)
        suffix_min = jnp.flip(jax.lax.cummin(jnp.flip(nxt)))
        next_start = jnp.concatenate(
            [suffix_min[1:], jnp.full((1,), n, dtype=suffix_min.dtype)])
        run_len = (next_start - run_start).astype(jnp.int32)
        # max duplicate-key run among LIVE build rows: 1 means the build
        # side is unique (a primary/dimension key) and probes can take the
        # no-expansion fast path (unique_inner_probe) — the executor
        # fetches this once per join
        max_run_live = jnp.max(jnp.where(jnp.arange(n, dtype=jnp.int32)
                                         < n_live_build, run_len, 0))
        # live-key min/max (u64 space): the executor fetches these with
        # max_run and, when the span is small (dense surrogate keys — every
        # TPC-H/DS key), builds a direct-address lookup table so probes
        # cost ONE gather instead of a sort-engine searchsorted pass
        live_key = ~b_dead
        kmin = jnp.min(jnp.where(live_key, bkey, u64max))
        kmax = jnp.max(jnp.where(live_key, bkey, jnp.uint64(0)))
        return (build, bkey_s, bperm, n_live_build, n_build_rows,
                build_has_null, run_len, max_run_live, kmin, kmax)
    return prep


_DENSE_SENTINEL = jnp.int32(0x7FFFFFFF)


def _dense_scatter(size: int, bkey_s, n_live, kmin, payload):
    """Shared scatter for the direct-address builders: dead positions and
    out-of-span keys route to the dropped slot `size`."""
    n = bkey_s.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    raw = (bkey_s - kmin).astype(jnp.int64)
    oob = (idx >= n_live) | (raw < 0) | (raw >= size)
    slot = jnp.where(oob, size, raw)
    return jnp.full(size, _DENSE_SENTINEL, jnp.int32) \
        .at[slot].min(payload, mode="drop")


def build_dense_table(size: int):
    """Direct-address lookup table for a sorted build: table[key - kmin] =
    position of that key's FIRST sorted occurrence (so run_len[pos] still
    yields the duplicate count), sentinel INT32_MAX elsewhere.

    The TPU analog of the reference's array-based lookup source for dense
    bigint keys (operator/join/... ArrayBasedLookupSource idea): one
    scatter at build time buys gather-only probes. Every TPC-H/DS join key
    is a dense surrogate (orderkey/partkey/.._sk), so this path carries
    the hot joins; sparse/hashed keys fall back to searchsorted."""

    def op(bkey_s, n_live, kmin):
        n = bkey_s.shape[0]
        return _dense_scatter(size, bkey_s, n_live, kmin,
                              jnp.arange(n, dtype=jnp.int32))
    return op


def _dense_lo(table: jnp.ndarray, kmin, pkey: jnp.ndarray) -> jnp.ndarray:
    """lower-bound analog via the dense table: position of pkey's first
    sorted occurrence, or a huge sentinel (>= any n_live) when absent."""
    size = table.shape[0]
    raw = (pkey - kmin).astype(jnp.int64)
    inb = (raw >= 0) & (raw < size)
    lo = jnp.take(table, jnp.clip(raw, 0, size - 1), mode="clip")
    return jnp.where(inb, lo, _DENSE_SENTINEL)


def hash_join(
    probe_keys: Sequence[int],
    build_keys: Sequence[int],
    join_type: str = JoinType.INNER,
    output_capacity: Optional[int] = None,
    verify_composite: bool = True,
    prepared: bool = False,
    null_aware: bool = True,
    lookup: str = "search",
    mxu_slots: Optional[int] = None,
    probe_out: Optional[Sequence[int]] = None,
    build_out: Optional[Sequence[int]] = None,
) -> Callable[[Page, Page], Tuple[Page, jnp.ndarray]]:
    """Build op(probe_page, build) -> (output_page, true_total_rows).

    `build` is a build Page, or (with prepared=True) the tuple produced by
    prepare_build — the executor sorts the build once and probes many pages.
    Output layout: probe columns ++ build columns (semi/anti: probe only).
    output_capacity: static result capacity; defaults to probe capacity.
    true_total_rows may exceed num_rows when the capacity was too small —
    the executor re-plans at a larger bucket (never silently truncates).

    `lookup` picks the probe strategy (exec/local_planner._prepare_probe
    routes by density/span): 'search' = sort-engine searchsorted,
    'dense' = one gather against a direct-address table (prepared[10]),
    'mxu' = blocked indicator matmuls against the per-key [count, pos]
    table (prepared[10], ops/join_mxu.py) — the matrix-unit probe.
    `mxu_slots` (prepared=False only — the mesh shard_map bodies, which
    prep inline) computes BOTH the matmul and the searchsorted probe
    and selects per shard with a branchless `where` on the traced key
    span: in-span shards use the MXU result, over-span shards the
    searchsorted one, inside one SPMD-uniform program.

    null_aware governs SEMI/ANTI/MARK null semantics (reference:
    sql/planner/QueryPlanner IN-predicate planning vs correlated-EXISTS
    decorrelation):
      True  — IN-subquery 3VL: a NULL probe key or a NULL in a non-empty
              build side makes the membership UNKNOWN, so ANTI keeps a
              non-matching row only when the build side is null-free, and a
              NULL probe key survives ANTI only against an EMPTY build
              (x NOT IN (empty) is TRUE even for NULL x).
      False — EXISTS semantics: NULL correlation keys simply never match
              (the correlated equality evaluates to NULL -> no inner row
              qualifies), so ANTI keeps every unmatched live probe row
              including NULL-key rows, and build-side NULLs are irrelevant.
    """
    probe_keys = tuple(probe_keys)
    build_keys = tuple(build_keys)
    composite = len(probe_keys) > 1

    def op(probe: Page, build) -> Tuple[Page, jnp.ndarray]:
        aux_table = None
        if prepared:
            if lookup in ("dense", "mxu"):
                aux_table = build[10]
            (build, bkey_s, bperm, n_live_build, n_build_rows,
             build_has_null, run_len, _max_run, kmin, kmax) = build[:10]
        else:
            (build, bkey_s, bperm, n_live_build, n_build_rows,
             build_has_null, run_len, _max_run, kmin, kmax) = \
                prepare_build(build_keys)(build)
        n_build = build.capacity
        n_probe = probe.capacity
        n_probe_cols = probe.num_columns
        cap = output_capacity or n_probe
        for pk, bk in zip(probe_keys, build_keys):
            pd = probe.column(pk).dictionary
            bd = build.column(bk).dictionary
            # content-fingerprint inequality (page.py round 10), not
            # object identity: pools with byte-identical values share one
            # code mapping, so joining across them is exact
            if pd is not None and bd is not None and pd != bd:
                raise NotImplementedError(
                    "string join keys across distinct dictionaries; "
                    "re-encode to a shared dictionary first")

        pkey, pnull = _key_u64(probe, probe_keys)

        p_dead = ~probe.row_mask() | pnull
        n_build_m1 = jnp.maximum(n_build - 1, 0)
        # the mesh in-program variant: shapes are static but the key span
        # is a traced per-shard value, so BOTH probe strategies compile
        # and lax.cond picks per shard (f32 exactness gate is static:
        # positions must stay under 2^24)
        inline_mxu = (mxu_slots is not None and not prepared
                      and n_build < (1 << 24))

        def _search_lookup():
            # ONE searchsorted over the live prefix (method="sort" routes
            # the lookup through the TPU sort engine — ~20x faster at
            # millions of keys than the default per-level binary-search
            # gathers); the upper bound comes from the build side's
            # precomputed run lengths
            s_lo = jnp.searchsorted(bkey_s, pkey, side="left",
                                    method="sort").astype(jnp.int32)
            s_lo_c = jnp.minimum(s_lo, n_build_m1)
            s_found = (jnp.take(bkey_s, s_lo_c, mode="clip") == pkey) & \
                (s_lo < n_live_build)
            s_cnt = jnp.where(s_found,
                              jnp.take(run_len, s_lo_c, mode="clip"), 0)
            return s_cnt, s_lo

        if lookup == "mxu" and aux_table is not None:
            # matrix-unit probe: blocked indicator matmuls against the
            # per-key [count, first-pos] table (ops/join_mxu.py)
            from trino_tpu.ops.join_mxu import matmul_lookup
            cnt, lo = matmul_lookup(aux_table, kmin, pkey)
            found = cnt > 0
            lo = jnp.where(found, lo, _DENSE_SENTINEL)
            lo_c = jnp.minimum(lo, n_build_m1)
            hi = lo + cnt
        elif inline_mxu:
            # both lookups compute and a per-shard `where` selects: the
            # key span is a traced per-shard value, and jnp.where keeps
            # the program SPMD-uniform (an earlier lax.cond formulation
            # miscompiled under shard_map fusion — any fusion barrier
            # "fixed" it — so the branchless select is also the safe
            # choice, at the cost of the searchsorted pass running on
            # in-span shards too)
            from trino_tpu.ops.join_mxu import (build_count_pos_table,
                                                matmul_lookup)
            table = build_count_pos_table(mxu_slots)(
                bkey_s, n_live_build, kmin)
            m_cnt, m_lo = matmul_lookup(table, kmin, pkey)
            s_cnt, s_lo = _search_lookup()
            span_ok = (kmax >= kmin) & \
                ((kmax - kmin) < jnp.uint64(mxu_slots))
            cnt = jnp.where(span_ok, m_cnt, s_cnt)
            lo = jnp.where(span_ok, m_lo, s_lo)
            found = cnt > 0
            lo = jnp.where(found, lo, _DENSE_SENTINEL)
            lo_c = jnp.minimum(lo, n_build_m1)
            hi = lo + cnt
        elif lookup == "dense" and aux_table is not None:
            # dense surrogate keys: ONE gather against the direct-address
            # table (slot identity implies key equality — no verify gather)
            lo = _dense_lo(aux_table, kmin, pkey)
            lo_c = jnp.minimum(lo, n_build_m1)
            found = lo < n_live_build
            hi = lo + jnp.where(found,
                                jnp.take(run_len, lo_c, mode="clip"), 0)
        else:
            cnt, lo = _search_lookup()
            lo_c = jnp.minimum(lo, n_build_m1)
            found = cnt > 0
            hi = lo + cnt
        lo = jnp.minimum(lo, n_live_build)
        hi = jnp.minimum(hi, n_live_build)
        counts = jnp.where(p_dead, 0, hi - lo).astype(jnp.int64)

        def anti_keep(matched: jnp.ndarray) -> jnp.ndarray:
            live = probe.row_mask()
            if null_aware:
                # NOT IN: non-null probe keeps iff unmatched AND build has
                # no NULLs; NULL probe keeps only against an empty build
                return live & jnp.where(
                    pnull, n_build_rows == 0, ~matched & ~build_has_null)
            # NOT EXISTS: unmatched live rows keep (NULL keys never match)
            return live & ~matched

        def mark_page(matched: jnp.ndarray) -> Page:
            if null_aware:
                return _mark_page(probe, matched, pnull, n_build_rows,
                                  build_has_null)
            value = matched & ~pnull
            mark = Column(value, None, T.BOOLEAN, None)
            return Page(tuple(probe.columns) + (mark,), probe.num_rows)

        if join_type in (JoinType.SEMI, JoinType.ANTI, JoinType.MARK) \
                and not (composite and verify_composite):
            # single-column keys: to_u64 is injective, hash match == key match
            if join_type == JoinType.MARK:
                return mark_page(counts > 0), probe.num_rows.astype(jnp.int64)
            if join_type == JoinType.SEMI:
                out = probe.filter((counts > 0) & ~p_dead)
            else:
                out = probe.filter(anti_keep(counts > 0))
            return out, out.num_rows.astype(jnp.int64)

        emit = counts
        if join_type in (JoinType.LEFT, JoinType.FULL):
            # unmatched live probe rows (incl. null keys) emit one null-extended row
            live_probe = probe.row_mask()
            emit = jnp.where(live_probe & (counts == 0), 1, counts)
            emit = jnp.where(live_probe, emit, 0)
        offsets = jnp.cumsum(emit)
        total = offsets[-1]
        starts = offsets - emit  # exclusive prefix

        out_idx = jnp.arange(cap, dtype=jnp.int64)
        # which probe row produced output slot j: last start <= j
        prow = jnp.searchsorted(offsets, out_idx, side="right",
                                method="sort").astype(jnp.int32)
        prow_c = jnp.minimum(prow, n_probe - 1)
        j_within = out_idx - jnp.take(starts, prow_c, mode="clip")
        brow_sorted = jnp.take(lo, prow_c, mode="clip") + j_within
        brow = jnp.take(bperm, jnp.minimum(brow_sorted, n_build - 1),
                        mode="clip").astype(jnp.int32)
        slot_live = out_idx < jnp.minimum(total, cap)
        matched = jnp.take(counts, prow_c, mode="clip") > 0

        if join_type in (JoinType.SEMI, JoinType.ANTI, JoinType.MARK):
            # composite keys: re-check real key equality on each expanded
            # candidate, then scatter-or back to probe rows. Exact whenever the
            # hash-expansion fits in cap (else total > cap -> executor re-runs
            # at a bigger bucket, same contract as INNER).
            keep = slot_live & matched
            for pk, bk in zip(probe_keys, build_keys):
                pv = jnp.take(probe.column(pk).values, prow_c, mode="clip")
                bv = jnp.take(build.column(bk).values, brow, mode="clip")
                keep = keep & (pv == bv)
            verified = jnp.zeros(n_probe, dtype=jnp.bool_).at[prow_c].max(
                keep, mode="drop")
            if join_type == JoinType.MARK:
                rows = probe.num_rows.astype(jnp.int64)
                return mark_page(verified), \
                    jnp.where(total <= cap, rows, total)
            if join_type == JoinType.SEMI:
                out = probe.filter(verified & ~p_dead)
            else:
                out = probe.filter(anti_keep(verified))
            rows = out.num_rows.astype(jnp.int64)
            return out, jnp.where(total <= cap, rows, total)

        real_match = slot_live & matched      # slot is a real hash candidate
        build_is_null = slot_live & ~matched  # LEFT/FULL null-extension rows

        # composite keys: re-check real key equality per candidate slot so
        # hash collisions are filtered exactly (single-key u64 is injective)
        keep = jnp.ones(cap, dtype=jnp.bool_)
        if composite and verify_composite:
            for pk, bk in zip(probe_keys, build_keys):
                pv = jnp.take(probe.column(pk).values, prow_c, mode="clip")
                bv = jnp.take(build.column(bk).values, brow, mode="clip")
                keep = keep & (pv == bv)
        verified_slot = real_match & keep

        if join_type in (JoinType.LEFT, JoinType.FULL) and composite \
                and verify_composite:
            # a probe row whose EVERY candidate was a hash collision must
            # still emit one null-extended row: rescue its first candidate
            # slot as the null-extension carrier
            verified_any = jnp.zeros(n_probe, dtype=jnp.bool_) \
                .at[prow_c].max(verified_slot, mode="drop")
            rescue = real_match & (j_within == 0) & \
                ~jnp.take(verified_any, prow_c, mode="clip")
            build_is_null = build_is_null | rescue
            keep = keep | rescue

        # PruneJoinColumns: gather only emitted channels (the probe/build
        # gathers at output capacity are the kernel's dominant cost)
        p_idx = range(probe.num_columns) if probe_out is None else probe_out
        b_idx = range(build.num_columns) if build_out is None else build_out
        pcols = tuple(probe.columns[i].gather(prow_c) for i in p_idx)
        bcols = []
        for i in b_idx:
            c = build.columns[i]
            g = c.gather(brow)
            valid = g.valid_mask() & ~build_is_null
            bcols.append(Column(g.values, valid, c.type, c.dictionary))
        out_rows = jnp.minimum(total, cap).astype(jnp.int32)
        out_page = Page(pcols + tuple(bcols), out_rows)

        if composite and verify_composite:
            # drop collision slots (null-extension slots pass: matched=False
            # there so keep was never narrowed for them... they start True)
            keep_final = jnp.where(real_match, keep, True)
            out_page = out_page.filter(keep_final)
            # overflow contract: if every hash match fit in cap, the filtered
            # count is the exact total; else keep the (over)count so the
            # executor re-plans at a larger capacity
            total = jnp.where(total <= cap,
                              out_page.num_rows.astype(jnp.int64), total)

        if join_type == JoinType.FULL:
            # which build rows found >=1 verified probe match (accumulated by
            # the executor across probe pages; unmatched rows emit at end)
            build_matched = jnp.zeros(n_build, dtype=jnp.bool_) \
                .at[brow].max(verified_slot, mode="drop")
            return out_page, total, build_matched
        return out_page, total

    return op


def prepare_build_spilled(build_keys: Sequence[int]):
    """Spilling build phase (HashBuilderOperator.java:163 spill state
    machine, re-thought for HBM): device memory holds ONLY what probing
    needs — the sorted u64 key array and the sort permutation — while the
    build's payload columns move to host RAM (the executor fetches them
    once and frees the device page). Probing then runs entirely against
    the key array; matched rows' build columns are gathered HOST-side at
    match count (attach_build_host), so a 150M-row build costs ~12 bytes/
    row of HBM instead of the full page + run-length structures.

    Returns op(build_page) -> (bkey_s, bperm, n_live, n_build_rows,
    build_has_null, is_unique)."""
    build_keys = tuple(build_keys)

    def prep(build: Page):
        bkey, bnull = _key_u64(build, build_keys)
        b_dead = ~build.row_mask() | bnull
        u64max = jnp.uint64(0xFFFFFFFFFFFFFFFF)
        bkey_masked = jnp.where(b_dead, u64max, bkey)
        bkey_s, b_dead_s, bperm = jax.lax.sort(
            [bkey_masked, b_dead,
             jnp.arange(build.capacity, dtype=jnp.int32)], num_keys=2)
        n_live = jnp.sum(~b_dead_s).astype(jnp.int32)
        live_b = build.row_mask()
        n_build_rows = jnp.sum(live_b).astype(jnp.int32)
        build_has_null = jnp.any(bnull & live_b)
        idx = jnp.arange(build.capacity, dtype=jnp.int32)
        dup = (bkey_s[1:] == bkey_s[:-1]) & (idx[1:] < n_live)
        is_unique = ~jnp.any(dup)
        live_key = ~b_dead
        kmin = jnp.min(jnp.where(live_key, bkey, u64max))
        kmax = jnp.max(jnp.where(live_key, bkey, jnp.uint64(0)))
        return (bkey_s, bperm, n_live, n_build_rows, build_has_null,
                is_unique, kmin, kmax)
    return prep


def build_dense_table_rows(size: int):
    """Spilled-dense build finisher: table[key - kmin] = ORIGINAL build row
    of that (unique) key, sentinel elsewhere. The probe then needs ONLY
    this table on device — no sorted keys, no permutation (4B/slot instead
    of 12B/row of HBM for a >threshold build)."""

    def op(bkey_s, bperm, n_live, kmin):
        return _dense_scatter(size, bkey_s, n_live, kmin, bperm)
    return op


def spilled_dense_probe(probe_keys: Sequence[int],
                        probe_out: Optional[Sequence[int]] = None):
    """Probe a spilled build through its dense row table: one gather per
    probe row. Returns (pre_page, found_mask, match_count) — compaction is
    deferred to the executor, which skips it entirely when every live
    probe row matched (the common fact-to-dimension case)."""
    probe_keys = tuple(probe_keys)

    def op(probe: Page, table, kmin):
        pkey, pnull = _key_u64(probe, probe_keys)
        p_dead = ~probe.row_mask() | pnull
        brow = _dense_lo(table, kmin, pkey)
        found = (brow != _DENSE_SENTINEL) & ~p_dead
        brow_col = Column(jnp.where(found, brow, 0).astype(jnp.int64),
                          None, T.BIGINT, None)
        p_idx = range(probe.num_columns) if probe_out is None else probe_out
        pre = Page(tuple(probe.columns[i] for i in p_idx) + (brow_col,),
                   probe.num_rows)
        return pre, found, jnp.sum(found).astype(jnp.int64)

    return op


_ANCHOR_LOG2 = 10


def _searchsorted_anchored(bkey_s: jnp.ndarray, pkey: jnp.ndarray
                           ) -> jnp.ndarray:
    """side='left' searchsorted for HUGE sorted arrays: method='sort'
    co-sorts the whole build array with every probe batch (a ~5GB
    workspace per call against a 150M-key build — the SF100 OOM), so
    instead (1) one sort-method search against a 1/2^10 anchor subsample,
    then (2) 2^10-window lower_bound via ~11 branchless gather rounds.
    Workspace is O(probe + build/1024); gathers run at probe size."""
    n = bkey_s.shape[0]
    stride = 1 << _ANCHOR_LOG2
    anchors = bkey_s[::stride]
    coarse = jnp.searchsorted(anchors, pkey, side="left", method="sort")
    pos = (jnp.maximum(coarse, 1) - 1) * stride
    # invariant: bkey_s[pos-1] < key (anchor strictly below); advance in
    # halving steps while the probe stays below the key
    step = stride
    while step > 0:
        nxt = pos + step
        v = jnp.take(bkey_s, jnp.minimum(nxt - 1, n - 1), mode="clip")
        advance = (nxt <= n) & (v < pkey)
        pos = jnp.where(advance, nxt, pos)
        step //= 2
    return pos


def spilled_unique_probe(probe_keys: Sequence[int],
                         probe_out: Optional[Sequence[int]] = None):
    """Probe phase against a spilled build: identical to unique_inner_probe
    but consuming only (bkey_s, bperm, n_live) — no build Page on device.
    Composite-key verification happens host-side in attach_build_host
    (the build columns live there). Returns (pre, found, count); the
    executor compacts (or skips compaction when all rows matched)."""
    probe_keys = tuple(probe_keys)

    def op(probe: Page, bkey_s, bperm, n_live):
        n_build = bkey_s.shape[0]
        pkey, pnull = _key_u64(probe, probe_keys)
        p_dead = ~probe.row_mask() | pnull
        lo = _searchsorted_anchored(bkey_s, pkey)
        lo_c = jnp.minimum(lo, jnp.maximum(n_build - 1, 0))
        found = (jnp.take(bkey_s, lo_c, mode="clip") == pkey) & \
            (lo < n_live) & ~p_dead
        brow = jnp.take(bperm, lo_c, mode="clip").astype(jnp.int64)
        brow_col = Column(brow, None, T.BIGINT, None)
        p_idx = range(probe.num_columns) if probe_out is None else probe_out
        pre = Page(tuple(probe.columns[i] for i in p_idx) + (brow_col,),
                   probe.num_rows)
        return pre, found, jnp.sum(found).astype(jnp.int64)

    return op


def attach_build_host(pre: Page, n_probe_cols: int, host_cols,
                      verify: Optional[Sequence[Tuple[int, int]]] = None,
                      emit: Optional[Sequence[int]] = None) -> Page:
    """Host-side attach for the spilled path: gather build columns from
    host numpy arrays at the matched rows' original indices and stage only
    the match-count-sized result. `host_cols` is [(values_np, valid_np or
    None, type, dictionary)]. `verify` = [(probe_ch, build_col_idx)] pairs
    re-checked for composite keys (hash collisions). `emit` selects which
    host_cols are emitted (default all) — verify-only key columns need not
    be staged back to device."""
    import numpy as np
    n = int(pre.num_rows)
    brow = np.asarray(
        jax.device_get(pre.columns[n_probe_cols].values[:max(n, 1)]))[:n] \
        .astype(np.int64)
    keep = None
    if verify:
        for pch, bci in verify:
            pv = np.asarray(jax.device_get(
                pre.columns[pch].values[:max(n, 1)]))[:n]
            bv = host_cols[bci][0][brow]
            eq = pv == bv
            keep = eq if keep is None else (keep & eq)
    if keep is not None and not keep.all():
        sel = np.nonzero(keep)[0]
        brow = brow[sel]
    else:
        sel = None
    cap = pre.capacity
    bcols = []
    emit_cols = host_cols if emit is None else [host_cols[i] for i in emit]
    for values, valid, typ, d in emit_cols:
        g = values[brow]
        v = valid[brow] if valid is not None else None
        bcols.append(Column.from_numpy(
            _pad_np(g, cap), typ,
            valid=None if v is None else _pad_np(v, cap), dictionary=d))
    pcols = pre.columns[:n_probe_cols]
    if sel is not None:
        keep_dev = jnp.zeros(cap, dtype=jnp.bool_) \
            .at[jnp.asarray(sel)].set(True)
        filtered = Page(pcols, pre.num_rows).filter(keep_dev)
        pcols = filtered.columns
        nrows = filtered.num_rows
    else:
        nrows = pre.num_rows
    return Page(tuple(pcols) + tuple(bcols), nrows)


def _pad_np(arr, cap):
    import numpy as np
    if len(arr) == cap:
        return arr
    out = np.zeros(cap, dtype=arr.dtype)
    out[:len(arr)] = arr
    return out


def unique_inner_probe(
    probe_keys: Sequence[int],
    build_keys: Sequence[int],
    verify_composite: bool = True,
    lookup: str = "search",
    probe_out: Optional[Sequence[int]] = None,
) -> Callable[[Page, tuple], Tuple[Page, jnp.ndarray, jnp.ndarray]]:
    """INNER-join probe against a UNIQUE build side (max key run == 1) —
    the dimension/primary-key case covering every TPC-H/DS fact-to-dim
    join. No cumsum expansion, no output-slot searchsorted, no
    capacity-sized gathers (round-4 profiling: those cost ~0.7s per
    MILLION probe rows in the general kernel). With lookup='dense' the
    searchsorted collapses to one gather against the direct-address table
    (prepared[10]); lookup='mxu' runs the same lookup as blocked
    indicator matmuls on the matrix unit (ops/join_mxu.py).

    Returns (pre_page, found_mask, match_count): pre_page is probe columns
    ++ a BIGINT `brow` channel at PROBE order. The executor compacts with
    one filter kernel — or skips compaction when every live row matched
    (count == num_rows; the common fact-to-dim case) — then runs
    attach_build at live size. Output can never overflow (<= probe rows),
    so no capacity re-run loop is needed."""
    probe_keys = tuple(probe_keys)
    build_keys = tuple(build_keys)
    composite = len(probe_keys) > 1

    def op(probe: Page, prepared):
        aux_table = prepared[10] if lookup in ("dense", "mxu") else None
        (build, bkey_s, bperm, n_live_build, n_build_rows,
         build_has_null, run_len, _max_run, kmin, _kmax) = prepared[:10]
        n_build = build.capacity
        for pk, bk in zip(probe_keys, build_keys):
            pd = probe.column(pk).dictionary
            bd = build.column(bk).dictionary
            # content-fingerprint inequality (page.py round 10), not
            # object identity: pools with byte-identical values share one
            # code mapping, so joining across them is exact
            if pd is not None and bd is not None and pd != bd:
                raise NotImplementedError(
                    "string join keys across distinct dictionaries; "
                    "re-encode to a shared dictionary first")
        pkey, pnull = _key_u64(probe, probe_keys)
        p_dead = ~probe.row_mask() | pnull
        n_build_m1 = jnp.maximum(n_build - 1, 0)
        if lookup == "mxu" and aux_table is not None:
            from trino_tpu.ops.join_mxu import matmul_lookup
            cnt, lo = matmul_lookup(aux_table, kmin, pkey)
            lo_c = jnp.minimum(lo, n_build_m1)
            found = (cnt > 0) & ~p_dead
        elif lookup == "dense" and aux_table is not None:
            lo = _dense_lo(aux_table, kmin, pkey)
            lo_c = jnp.minimum(lo, n_build_m1)
            found = (lo < n_live_build) & ~p_dead
        else:
            lo = jnp.searchsorted(bkey_s, pkey, side="left", method="sort")
            lo_c = jnp.minimum(lo, n_build_m1)
            found = (jnp.take(bkey_s, lo_c, mode="clip") == pkey) & \
                (lo < n_live_build) & ~p_dead
        brow = jnp.take(bperm, lo_c, mode="clip").astype(jnp.int64)
        if composite and verify_composite:
            # unique build: at most one candidate — verify it directly
            for pk, bk in zip(probe_keys, build_keys):
                bv = jnp.take(build.column(bk).values, brow, mode="clip")
                found = found & (probe.column(pk).values == bv)
        brow_col = Column(jnp.where(found, brow, 0), None, T.BIGINT, None)
        p_idx = range(probe.num_columns) if probe_out is None else probe_out
        pre = Page(tuple(probe.columns[i] for i in p_idx) + (brow_col,),
                   probe.num_rows)
        return pre, found, jnp.sum(found).astype(jnp.int64)

    return op


def build_key_bounds(build_keys: Sequence[int]):
    """Dynamic-filter source (operator/DynamicFilterSourceOperator.java +
    server/DynamicFilterService.java:102 analog, collapsed to the
    single-controller design): after the build side is collected, its key
    min/max become device scalars the probe-side scan stream filters by —
    no coordinator round trip, the scalars never leave the device.

    Exact-set pruning (Trino's small-build IN-list filter) is deliberately
    NOT a separate pass here: the unique-build probe path already compacts
    non-matching probe rows with one stable sort before any build-column
    gather, which is the same work an exact-set semi prefilter would do."""
    build_keys = tuple(build_keys)

    def op(build: Page):
        c = build.column(build_keys[0])
        live = build.row_mask()
        if c.valid is not None:
            live = live & c.valid
        v = c.values
        if jnp.issubdtype(v.dtype, jnp.integer):
            big, small = jnp.iinfo(v.dtype).max, jnp.iinfo(v.dtype).min
        else:
            big, small = jnp.inf, -jnp.inf
        lo = jnp.min(jnp.where(live, v, big))
        hi = jnp.max(jnp.where(live, v, small))
        return lo, hi

    return op


def range_prefilter(probe_key: int):
    """Probe-side dynamic-filter application: drop rows whose key can't be
    in [lo, hi] (NULL keys never match an INNER join, so they drop too)."""

    def op(page: Page, lo, hi) -> Page:
        c = page.column(probe_key)
        keep = (c.values >= lo) & (c.values <= hi)
        if c.valid is not None:
            keep = keep & c.valid
        return page.filter(keep)

    return op


def attach_build(n_probe_cols: int,
                 build_out: Optional[Sequence[int]] = None
                 ) -> Callable[[Page, tuple], Page]:
    """Second phase of the unique-build fast path: gather build columns
    (only the emitted channels) at the compacted (live-size) brow indices
    and restore the probe++build output layout."""

    def op(pre: Page, prepared) -> Page:
        build = prepared[0]
        brow = pre.columns[n_probe_cols].values.astype(jnp.int32)
        live = pre.row_mask()
        brow = jnp.where(live, brow, 0)
        b_idx = range(build.num_columns) if build_out is None else build_out
        bcols = tuple(build.columns[i].gather(brow) for i in b_idx)
        return Page(tuple(pre.columns[:n_probe_cols]) + bcols, pre.num_rows)

    return op


def unmatched_build_page(probe_meta: Sequence[Tuple[T.Type, object]],
                         ) -> Callable[[Page, jnp.ndarray], Page]:
    """FULL-join finisher (operator/join/LookupOuterOperator.java analog):
    emit build rows never matched by any probe page, null-extended on the
    probe side. `matched` is the OR of per-page build_matched masks;
    `probe_meta` is (type, dictionary) per probe column so null columns keep
    the stream's dictionaries (concat/union safety downstream)."""
    probe_meta = tuple(probe_meta)

    def op(build: Page, matched: jnp.ndarray) -> Page:
        kept = build.filter(~matched & build.row_mask())
        cap = kept.capacity
        pcols = tuple(
            Column(jnp.zeros(cap, dtype=t.dtype),
                   jnp.zeros(cap, dtype=jnp.bool_), t, d)
            for t, d in probe_meta)
        return Page(pcols + tuple(kept.columns), kept.num_rows)

    return op
