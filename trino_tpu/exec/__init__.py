"""Execution engine: plan -> operator pipelines over device Pages.

Reference parity: sql/planner/LocalExecutionPlanner.java:420 (plan fragment ->
DriverFactories) + operator/Driver.java's page loop. The TPU design replaces
the time-sliced operator interpreter with composed, jitted per-page device
functions (XLA fuses each chain; SURVEY §2.5 'TPU build' column).
"""

from trino_tpu.exec.runner import LocalQueryRunner  # noqa: F401
