"""Query memory accounting.

Reference parity: memory/MemoryPool.java:44 + lib/trino-memory-context
(AggregatedMemoryContext tree) + ExceededMemoryLimitException — every
blocking materialization (join build side, aggregation/sort/window collect,
exchange buffers) reserves its page bytes against the session's
`query_max_memory` before the device call, and the query fails with the
reference's "Query exceeded per-node memory limit" error when the
reservation would overflow.

TPU framing: the pool models HBM, the scarce resource a fused streaming
pipeline does NOT consume (pages flow through one kernel) but blocking
operators do. Reservations are tracked per operator tag so the error names
the offender, and freed when an operator's output is consumed (operator
scopes call free()).
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional

from trino_tpu.errors import EXCEEDED_LOCAL_MEMORY_LIMIT, TrinoError


class ExceededMemoryLimitError(TrinoError, RuntimeError):
    """io.trino.ExceededMemoryLimitException analog (RuntimeError kept in
    the bases for pre-taxonomy callers)."""

    CODE = EXCEEDED_LOCAL_MEMORY_LIMIT


@contextlib.contextmanager
def degrade_to_spill(session):
    """Graceful degradation for a fragment retry after an
    ExceededMemoryLimitError: force the spill path on and pull every spill
    threshold under the memory limit, so blocking operators flush to host
    partitions instead of materializing over-limit device pages
    (TaskExecutor's revoke-memory-then-retry analog). Restores the
    session's property bag on exit."""
    saved = dict(session.properties)
    limit = int(session.get("query_max_memory"))
    threshold = max(1, limit // 4)
    session.properties["spill_enabled"] = True
    for prop in ("join_spill_threshold_bytes", "agg_spill_threshold_bytes",
                 "sort_spill_threshold_bytes"):
        session.properties[prop] = min(int(session.get(prop)), threshold)
    try:
        yield
    finally:
        session.properties.clear()
        session.properties.update(saved)


def _fmt_bytes(n: int) -> str:
    units = ("B", "kB", "MB", "GB", "TB")
    v = float(n)
    for u in units:
        if abs(v) < 1024 or u == units[-1]:
            return f"{int(v)}{u}" if u == "B" else f"{v:.2f}{u}"
        v /= 1024
    return f"{n}B"


def page_bytes(page) -> int:
    """Device bytes of one Page (sum of Column.nbytes)."""
    return sum(col.nbytes for col in page.columns)


class QueryMemoryContext:
    """Single-query reservation ledger checked against query_max_memory."""

    def __init__(self, limit_bytes: Optional[int]):
        self.limit = int(limit_bytes) if limit_bytes is not None else None
        self.reserved = 0
        self.peak = 0
        self.by_tag: Dict[str, int] = {}

    def reserve(self, nbytes: int, tag: str = "operator") -> None:
        nbytes = int(nbytes)
        if nbytes <= 0:
            return
        if self.limit is not None and self.reserved + nbytes > self.limit:
            raise ExceededMemoryLimitError(
                f"Query exceeded per-node memory limit of "
                f"{_fmt_bytes(self.limit)} [{tag} requested "
                f"{_fmt_bytes(nbytes)}, reserved "
                f"{_fmt_bytes(self.reserved)}]")
        self.reserved += nbytes
        self.by_tag[tag] = self.by_tag.get(tag, 0) + nbytes
        self.peak = max(self.peak, self.reserved)

    def free(self, nbytes: int, tag: str = "operator") -> None:
        nbytes = int(nbytes)
        self.reserved = max(0, self.reserved - nbytes)
        if tag in self.by_tag:
            self.by_tag[tag] = max(0, self.by_tag[tag] - nbytes)
