"""HTTP /v1/statement server over a query runner.

Reference parity: server/protocol/ExecutingStatementResource.java +
dispatcher/QueuedStatementResource.java:95 + DispatchManager.java:140 —
POST /v1/statement submits SQL, the client then follows `nextUri` (GET)
until the response carries no `nextUri`; DELETE on the page URI cancels.
Session state travels in X-Trino-* headers both ways (Set-Session /
Clear-Session on SET/RESET), keeping the server stateless across requests
the way the reference's dispatcher is.

Dispatch model (round 7): queries submit into a RESOURCE-GROUP tree
(exec/resource_groups.py — the InternalResourceGroupManager analog) and a
pool of `max_running` executor threads drains it by weighted-fair
selection. Each query executes on a `runner.for_query()` clone (private
session + fault-tolerance state over shared catalogs), so independent
queries genuinely interleave: JAX dispatch is thread-safe and per-query
device programs queue on the device stream. Admission control: every
level of a query's group chain bounds its queue (`max_queued`) and an
over-limit submit fails with QUERY_QUEUE_FULL
(InternalResourceGroup.canQueueMore); `hard_concurrency` caps a group's
simultaneously running queries and `soft_memory_limit_bytes` stops
admitting queries from a group whose node-pool usage is over the line.
The query's group comes from the `resource_group` session property
(X-Trino-Session header).

Fault tolerance (round 6): the registry is lock-guarded (HTTP threads and
the executors mutate it concurrently) and pruned past `keep` terminal
queries (a pruned id answers 410 Gone, not 404). Every query registers in
the process-wide TRACKER under its server id, so system.runtime.queries
reflects server traffic. DELETE on a RUNNING query sets its cancel event;
the runner observes it at the next cooperative checkpoint
(exec/deadline.py), transitions the query to CANCELED, and frees the
executor for the next queued query. `query_timeout_s` is the per-query
wall-clock cap: one hung query fails with EXCEEDED_TIME_LIMIT instead of
wedging an executor forever.

Serving tier (trino_tpu/serve/): three layers above dispatch make the
repeated-prepared-statement hot path approximately one HTTP round trip:

- STREAMING statement lifecycle: each executing query writes result rows
  into a bounded ring buffer (serve/streaming.ResultStream) as operators
  produce them; `nextUri` paging serves chunk `token` straight off the
  ring, so the client sees its first page BEFORE the query completes and
  a slow client pauses the producer at a cooperative checkpoint instead
  of forcing the server to buffer the full result. Wire states:
  QUEUED -> RUNNING (producing) -> FINISHING (producer done, ring
  draining) -> FINISHED.
- RESULT-CACHE fast path: POST probes the runner's result-set cache
  (serve/caches.py) on the HTTP thread before touching the dispatch
  queue; a hit answers FINISHED — often with the data inline in the POST
  response — with zero planning, zero compiles, zero execution, and no
  executor handoff. INSERT/DDL evicts through the plan cache's
  invalidation hooks, so a stale cached answer is impossible.
- WEIGHTED CPU scheduling: each executor slice's wall charges to the
  query's resource group (ResourceGroupManager.charge), advancing the
  stride pass by seconds/weight — groups share executor time by weight,
  not just by admission counts.

A warmup manifest (`warmup_manifest=` / $TRINO_TPU_WARMUP_MANIFEST,
serve/warmup.py) PREPAREs and pre-executes representative statements at
startup so the first real request binds into a warm plan cache and warm
kernels. OTLP span export (obs/otlp.py) wires in when configured.
"""

from __future__ import annotations

import itertools
import json
import re
import socket
import threading
import time
import uuid
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from trino_tpu.errors import QueryCanceledError
from trino_tpu.exec.resource_groups import ResourceGroupManager
from trino_tpu.exec.runner import MaterializedResult
from trino_tpu.serve.streaming import ResultStream
from trino_tpu.server import protocol

PAGE_ROWS = 1000

# live servers, for the /v1/metrics serving-tier gauges (weak: a stopped
# server's registry entry disappears with it)
_SERVERS: "weakref.WeakSet[TrinoServer]" = weakref.WeakSet()


def _server_gauges():
    """Scrape-time gauges over every live server: registry depth by
    state (the dispatch queue-depth signal alongside the per-group
    queued/running gauges)."""
    for srv in list(_SERVERS):
        with srv._lock:
            states: Dict[str, int] = {}
            for q in srv._queries.values():
                states[q.state] = states.get(q.state, 0) + 1
        for state, n in sorted(states.items()):
            yield ("trino_tpu_server_queries",
                   "Registered server queries by protocol state.",
                   n, {"state": state, "port": srv.port})

_SET_SESSION = re.compile(r"^\s*set\s+session\s+(\w+)\s*=\s*(.+?)\s*$",
                          re.IGNORECASE | re.DOTALL)
_RESET_SESSION = re.compile(r"^\s*reset\s+session\s+(\w+)\s*$",
                            re.IGNORECASE)
_PREPARE = re.compile(
    r'^\s*prepare\s+("(?:[^"]|"")*"|\w+)\s+from\s+(.+?)\s*$',
    re.IGNORECASE | re.DOTALL)
_DEALLOCATE = re.compile(
    r'^\s*deallocate\s+prepare\s+("(?:[^"]|"")*"|\w+)\s*$',
    re.IGNORECASE)


class _Query:
    def __init__(self, query_id: str, slug: str, sql: str, headers: dict):
        self.query_id = query_id
        self.slug = slug
        self.sql = sql
        self.headers = headers
        self.state = "QUEUED"
        self.result: Optional[MaterializedResult] = None
        self.error: Optional[dict] = None
        self.update_type: Optional[str] = None
        self.set_session: Optional[tuple] = None
        self.clear_session: Optional[str] = None
        # prepared-statement protocol state (StatementClientV1): a
        # PREPARE echoes (name, sql) back via X-Trino-Added-Prepare so
        # the stateless client re-sends it per request; DEALLOCATE
        # echoes the name via X-Trino-Deallocated-Prepare
        self.added_prepare: Optional[tuple] = None
        self.deallocated_prepare: Optional[str] = None
        # streaming result ring (serve/streaming.ResultStream): when the
        # runner opens it, paging serves chunks off the ring instead of
        # q.result; stays unopened for non-query statements, writers,
        # retry-capable sessions, and result-cache hits
        self.stream: Optional[ResultStream] = None
        self.cancelled = False
        # crossed by threads: DELETE (HTTP) cancels it, the runner's
        # cooperative checkpoints (executor thread) observe it; the
        # CancelEvent carries the request timestamp the runner turns
        # into preempt_latency_ms
        from trino_tpu.exec.deadline import CancelEvent
        self.cancel_event = CancelEvent()
        self.info = None               # QueryTracker entry
        self.started = time.monotonic()

    @property
    def elapsed_ms(self) -> int:
        return int((time.monotonic() - self.started) * 1000)

    @property
    def done(self) -> bool:
        # FINISHING counts: execution is over (cancel is a no-op, the
        # entry is prunable past `keep` — pruning an undrained stream
        # loses its chunks exactly like pruning buffered results)
        return self.state in ("FINISHED", "FINISHING", "FAILED",
                              "CANCELED")


class TrinoServer:
    """Wire-compatible statement server wrapping a query runner."""

    def __init__(self, runner, host: str = "127.0.0.1", port: int = 0,
                 max_queued: int = 200, keep: int = 200,
                 query_timeout_s: Optional[float] = None,
                 max_running: int = 4,
                 resource_groups: Optional[ResourceGroupManager] = None,
                 resource_groups_path: Optional[str] = None,
                 compilation_cache_dir: Optional[str] = None,
                 plan_cache_max_entries: Optional[int] = None,
                 streaming: bool = True,
                 result_cache: bool = True,
                 scan_cache: bool = True,
                 table_cache: bool = True,
                 stream_ring_chunks: int = 16,
                 stream_stall_timeout_s: float = 300.0,
                 warmup_manifest=None,
                 otlp_export: Optional[str] = None,
                 metrics_wall_buckets=None,
                 trace_dir: Optional[str] = None,
                 history_max_entries: Optional[int] = None,
                 drain_timeout_s: float = 10.0,
                 drain_idle_grace_s: float = 1.0,
                 listen_fd: Optional[int] = None):
        self.runner = runner
        # serving tier defaults: the server IS the production front door,
        # so result/scan caching default ON for server sessions (clones
        # inherit through the session property bag); direct runners keep
        # the metadata.py defaults (off)
        self.streaming_enabled = bool(streaming)
        self.stream_ring_chunks = int(stream_ring_chunks)
        self.stream_stall_timeout_s = float(stream_stall_timeout_s)
        self.result_cache_enabled = bool(result_cache)
        if result_cache:
            runner.session.set("result_cache_enabled", True)
        if scan_cache:
            runner.session.set("scan_cache_enabled", True)
        if table_cache:
            # the device-resident hot-table tier (exec/table_cache.py):
            # server sessions promote hot columns into HBM across
            # queries; warmup `tables:` entries preload them at start()
            runner.session.set("table_cache_enabled", True)
        # warmup manifest (serve/warmup.py): held here, applied in
        # start() BEFORE the executors spin up so the first real request
        # finds a warm plan cache and warm kernels
        import os as _os_env
        if warmup_manifest is None:
            warmup_manifest = _os_env.environ.get(
                "TRINO_TPU_WARMUP_MANIFEST") or None
        self._warmup_manifest = warmup_manifest
        self.warmup_report: List[dict] = []
        # OTLP span export (obs/otlp.py): off unless configured here or
        # via $TRINO_TPU_OTLP_ENDPOINT / $TRINO_TPU_OTLP_FILE
        from trino_tpu.obs.otlp import install_otlp_exporter
        self.otlp_exporter = install_otlp_exporter(otlp_export)
        # Chrome-trace export: a server constructed with trace_dir
        # exports EVERY query's span tree as Perfetto-loadable JSON into
        # that directory (QueryInfo.trace_file / GET
        # /v1/query/{id}/trace); the session property rides to
        # for_query() clones through the shared property bag
        if trace_dir is not None:
            runner._trace_dir = str(trace_dir)
            runner.session.set("trace_export", True)
        # query-history retention (obs/history.py): deployment-level
        # bound on the completed-queries ring, same owning-runner
        # discipline as plan_cache_max_entries
        if history_max_entries is not None:
            from trino_tpu.obs.history import HISTORY
            runner.session.set("history_max_entries",
                               int(history_max_entries))
            HISTORY.resize(int(history_max_entries))
        # deployment-tuned wall histogram buckets: the process default
        # is session-independent ($TRINO_TPU_METRICS_WALL_BUCKETS or the
        # static obs/metrics.DEFAULT_WALL_BUCKETS); a server that knows
        # its workload's latency envelope re-buckets here (the family
        # resets — restart semantics, see Histogram.set_buckets)
        if metrics_wall_buckets is not None:
            from trino_tpu.obs.metrics import set_wall_buckets
            set_wall_buckets(metrics_wall_buckets)
        # server-level plan-cache sizing: per-request X-Trino-Session
        # headers land on `for_query()` clones, which never resize the
        # SHARED cache (one client must not evict everyone's warm plans),
        # so the deployment bound is a constructor parameter on the
        # owning runner. The session property is set too: if the base
        # runner ever plans directly, its miss path re-reads the property
        # and must not snap the bound back to the default.
        if plan_cache_max_entries is not None:
            runner.session.set("plan_cache_max_entries",
                               int(plan_cache_max_entries))
            # resize (under the cache lock), not a bare attribute write:
            # a shrink over an already-warm runner must evict now
            runner._plan_cache.resize(int(plan_cache_max_entries))
        # cross-process compile reuse: point XLA's on-disk cache at the
        # given directory (or $TRINO_TPU_COMPILATION_CACHE_DIR) so a cold
        # server start reloads compiled executables instead of recompiling
        # — with literal hoisting the cached programs are literal-free, so
        # the disk entries cover every parameter variant of a shape. The
        # in-process jit-cache LRU (exec/jit_cache.py) layers above this.
        import os as _os
        if compilation_cache_dir is None:
            compilation_cache_dir = _os.environ.get(
                "TRINO_TPU_COMPILATION_CACHE_DIR")
        if compilation_cache_dir:
            import trino_tpu
            trino_tpu.enable_persistent_cache(compilation_cache_dir)
        # size the node pool from the backend's measured per-device
        # memory at server startup (HBM minus scan-cache budget); CPU
        # backends keep the static default (exec/memory.autosize_node_pool)
        from trino_tpu.exec.memory import autosize_node_pool
        autosize_node_pool()
        self.keep = keep
        self.query_timeout_s = query_timeout_s
        self.max_running = max(1, int(max_running))
        # the group tree this server dispatches through; callers may hand
        # in a preconfigured manager (group limits/weights) or a JSON
        # config file (`resource_groups.path` — the file-based
        # ResourceGroupConfigurationManager analog). max_queued stays the
        # SERVER-WIDE admission bound (round-5 contract) on top of
        # per-group budgets
        if resource_groups is None and resource_groups_path is not None:
            resource_groups = ResourceGroupManager.from_file(
                resource_groups_path, default_max_queued=max_queued,
                max_total_queued=max_queued)
        self.groups = resource_groups or ResourceGroupManager(
            default_max_queued=max_queued, max_total_queued=max_queued)
        # resource-group config hot-reload (round 14): an edited config
        # re-applies on mtime change WITHOUT a restart — fleet-wide
        # quota/limit changes don't need a rolling restart. Checked
        # (throttled) on the POST path, through the SAME FileWatch
        # primitive the fleet's quota maps use so engine and workers
        # cannot drift on when an edit takes effect.
        from trino_tpu.fleet.registry import FileWatch
        self._rg_path = resource_groups_path
        self._rg_watch = FileWatch(resource_groups_path)
        self._rg_reloads = 0
        # graceful drain (round 14): stop() stops accepting, then lets
        # RUNNING queries and actively-consumed result streams finish
        # before teardown. `drain_idle_grace_s` bounds how long an
        # ABANDONED stream (no page request) holds the drain.
        self.drain_timeout_s = float(drain_timeout_s)
        self.drain_idle_grace_s = float(drain_idle_grace_s)
        self.draining = threading.Event()
        # fleet integration seam: when set (fleet/server.py), the
        # result-cache fast path's per-group QPS quota check routes to
        # the FLEET-WIDE shared-memory buckets instead of the manager's
        # in-process ones, so engine-landed and worker-landed hits drain
        # one bucket per group
        self.fast_path_quota = None
        self._lock = threading.Lock()
        self._queries: Dict[str, _Query] = {}
        self._pruned: Dict[str, None] = {}   # ordered set of purged ids
        self._seq = itertools.count(1)
        self._stopping = threading.Event()
        handler = self._make_handler()
        # ThreadingHTTPServer's handler threads are daemonic, so
        # server_close() after the drain below never blocks on a parked
        # keep-alive connection
        if listen_fd is None:
            self._httpd = ThreadingHTTPServer((host, port), handler)
        else:
            # adopt an ALREADY-LISTENING socket received over SCM_RIGHTS
            # (fleet/handoff.py): the kernel accept queue — including
            # connections that arrived while no process was accepting —
            # transfers with the fd, which is what makes a planned
            # engine swap zero-drop. bind_and_activate=False skips
            # bind/listen; the placeholder socket is swapped for the fd.
            self._httpd = ThreadingHTTPServer((host, port), handler,
                                              bind_and_activate=False)
            placeholder = self._httpd.socket
            self._httpd.socket = socket.socket(fileno=listen_fd)
            placeholder.close()
            self._httpd.server_address = \
                self._httpd.socket.getsockname()[:2]
            self._httpd.server_name, self._httpd.server_port = \
                self._httpd.server_address
        self._thread: Optional[threading.Thread] = None
        self._executors: List[threading.Thread] = []
        _SERVERS.add(self)
        from trino_tpu.obs.metrics import REGISTRY
        REGISTRY.register_gauges(_server_gauges)   # idempotent

    # ---------------------------------------------------------- lifecycle

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def base_uri(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "TrinoServer":
        if self._warmup_manifest is not None:
            # synchronous, pre-executor: by the time start() returns, the
            # manifest's shapes are PREPAREd (shared map), planned (plan
            # cache), and compiled (jit cache, persistent-cache-backed)
            from trino_tpu.serve.warmup import apply_warmup
            self.warmup_report = apply_warmup(self.runner,
                                              self._warmup_manifest)
        for i in range(self.max_running):
            th = threading.Thread(target=self._drain, daemon=True,
                                  name=f"query-executor-{i}")
            th.start()
            self._executors.append(th)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, drain_timeout_s: Optional[float] = None) -> None:
        """Graceful shutdown with drain (round 14): stop accepting new
        connections, reject new statements, then let in-flight work
        finish before teardown — RUNNING queries complete, and open
        `nextUri` result streams keep serving pages off still-open
        connections until drained (or abandoned past the idle grace).
        Queued-but-unstarted queries are canceled (they never produced
        anything a client could lose), and whatever is left at the
        drain deadline is canceled cooperatively. `drain_timeout_s=0`
        restores the old immediate-teardown behavior."""
        drain_s = self.drain_timeout_s if drain_timeout_s is None \
            else float(drain_timeout_s)
        # "stop accepting" means STATEMENTS, not connections: clients
        # without keep-alive open a fresh connection per nextUri page,
        # so the listener must keep serving GET/DELETE until the drain
        # completes — new POSTs answer SERVER_SHUTTING_DOWN immediately
        self.draining.set()
        deadline = time.monotonic() + max(drain_s, 0.0)
        with self._lock:
            queries = list(self._queries.values())
        for q in queries:            # never-started queries just cancel
            if q.state == "QUEUED":
                q.cancelled = True
                q.cancel_event.cancel()
        while time.monotonic() < deadline:
            if not self._drain_pending():
                break
            time.sleep(0.05)
        with self._lock:
            leftovers = [q for q in self._queries.values() if not q.done]
        for q in leftovers:          # past the deadline: cancel, don't hang
            q.cancelled = True
            q.cancel_event.cancel()
        self._httpd.shutdown()
        self._stopping.set()
        for th in self._executors:
            th.join(timeout=10)
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        if self.otlp_exporter is not None:
            # the listener registry holds strong references: a stopped
            # server's exporter would keep exporting (and a restarted
            # one would double-export) every later query in the process
            from trino_tpu.obs.otlp import uninstall_otlp_exporter
            uninstall_otlp_exporter(self.otlp_exporter)
            self.otlp_exporter = None

    def _drain_pending(self) -> bool:
        """True while something a client could still lose is in flight:
        a RUNNING query, or an opened result stream that is not drained
        AND has seen consumer progress within the idle grace (an
        abandoned stream — client gone without DELETE — must not hold
        the drain for the full deadline; its query is canceled by the
        deadline sweep or the stall guard)."""
        now = time.monotonic()
        with self._lock:
            queries = list(self._queries.values())
        for q in queries:
            if q.state == "RUNNING":
                stream = q.stream
                if stream is not None and stream.opened and \
                        now - stream.last_consumer_contact > \
                        self.drain_idle_grace_s:
                    q.cancel_event.cancel()   # parked on a gone client
                    continue
                return True
            stream = q.stream
            if stream is not None and stream.opened \
                    and not stream.drained and q.error is None \
                    and not q.cancelled:
                if now - stream.last_consumer_contact <= \
                        self.drain_idle_grace_s:
                    return True    # actively consumed: let it finish
        return False

    def _maybe_reload_groups(self) -> None:
        """Resource-group config hot-reload: re-apply the JSON file when
        its mtime changes (throttled to one stat/s). A malformed or
        deleted file logs a warning and keeps the previous tree — an
        operator mishap must not strip a production server of its
        limits (quota MAPS are declarative and clear instead; see
        FileWatch's docstring for the split)."""
        if not self._rg_watch.changed():
            return
        import json as _json
        try:
            with open(self._rg_path) as fh:
                tree = _json.load(fh)
            # validate the WHOLE tree on a throwaway manager first: a
            # typo in group B must not leave group A half-reconfigured
            # (configure_from_dict applies specs sequentially)
            from trino_tpu.exec.resource_groups import _MANAGERS
            staged = ResourceGroupManager()
            _MANAGERS.discard(staged)   # not a live manager: keep it
            # out of system.runtime.resource_groups and the gauges
            staged.configure_from_dict(tree)
            self.groups.configure_from_dict(tree)
            self._rg_reloads += 1
        except Exception as e:   # noqa: BLE001 — keep the old config
            import logging
            logging.getLogger("trino_tpu.server").warning(
                "resource-group config reload failed for %s: %s",
                self._rg_path, e)

    # ---------------------------------------------------------- execution

    @staticmethod
    def _session_overrides(headers: dict) -> dict:
        """Parse the X-Trino-Session header once for everyone (reference
        wire format, ProtocolHeaders/StatementClientV1: comma-separated
        key=value pairs, values URL-encoded so raw commas never appear
        inside a value)."""
        from urllib.parse import unquote
        overrides = {}
        for part in headers.get("x-trino-session", "").split(","):
            if "=" in part:
                k, _, v = part.partition("=")
                overrides[k.strip()] = unquote(v.strip())
        return overrides

    def _group_for(self, headers: dict) -> str:
        """The query's resource group: the `resource_group` key of the
        client's X-Trino-Session header, else the base session default."""
        group = self._session_overrides(headers).get("resource_group")
        if group:
            return group
        try:
            return str(self.runner.session.get("resource_group"))
        except Exception:
            return "global"

    def _new_query_id(self) -> str:
        day = time.strftime("%Y%m%d")
        return f"{day}_{next(self._seq):06d}_{uuid.uuid4().hex[:5]}"

    def _submit(self, sql: str, headers) -> _Query:
        """Admit + enqueue (DispatchManager.createQuery analog): returns
        immediately with the QUEUED query; an executor-pool worker runs
        it after weighted-fair selection from its resource group."""
        from trino_tpu.exec.query_tracker import TRACKER
        qid = self._new_query_id()
        # lower-cased snapshot: header lookup must stay case-insensitive
        # after leaving the email.Message (HTTP header names are)
        q = _Query(qid, uuid.uuid4().hex[:12], sql,
                   {k.lower(): v for k, v in headers.items()})
        user = q.headers.get("x-trino-user", "user")
        # resolve the group BEFORE registering: the query_created event
        # fires from begin() and must carry the resource group
        group = self._group_for(q.headers)
        q.info = TRACKER.begin(sql, user=user, query_id=qid,
                               resource_group=group)
        with self._lock:
            self._queries[qid] = q
            self._prune_locked()
        if not self.groups.submit(group, q, qid):
            q.state = "FAILED"
            q.error = protocol.error_json(
                f"Too many queued queries for resource group {group!r}",
                error_name="QUERY_QUEUE_FULL",
                error_code=131074, error_type="INSUFFICIENT_RESOURCES")
            TRACKER.fail(q.info, "Too many queued queries",
                         error_name="QUERY_QUEUE_FULL")
        return q

    def _try_cached(self, sql: str, headers) -> Optional[_Query]:
        """POST-time result-cache probe — the serving tier's hot path.
        A hit is answered on the HTTP thread: no dispatch queue, no
        executor handoff, zero planning, zero compiles, zero execution
        (admission control is skipped too: a cache hit consumes no
        executor resources to admit). Any wrinkle — probe miss, parse
        error, header trouble — returns None and the normal dispatch
        path decides, so failures surface exactly as they always did."""
        from trino_tpu.exec.query_tracker import TRACKER
        if not self.result_cache_enabled:
            return None
        # cheap prefix gate: only statement kinds peek_cached_result can
        # resolve are worth a probe — DDL/INSERT/SET/PREPARE skip the
        # clone + parse entirely on the dispatch-bound path
        head = sql.lstrip()[:8].upper()
        if not head.startswith(("SELECT", "EXECUTE", "WITH", "VALUES",
                                "(", "TABLE")):
            return None
        hdrs = {k.lower(): v for k, v in headers.items()}
        try:
            runner = self.runner.for_query()
            session = runner.session
            catalog = hdrs.get("x-trino-catalog")
            schema = hdrs.get("x-trino-schema")
            if catalog:
                session.catalog = catalog
            if schema:
                session.schema = schema
            from trino_tpu.metadata import SESSION_PROPERTY_DEFAULTS
            for k, v in self._session_overrides(hdrs).items():
                if k in SESSION_PROPERTY_DEFAULTS:
                    session.set(k, v)
            self._apply_prepared_header(runner, hdrs)
            entry = runner.peek_cached_result(sql)
        except Exception:   # noqa: BLE001 — defer to the dispatch path
            return None
        if entry is None:
            return None
        qid = self._new_query_id()
        q = _Query(qid, uuid.uuid4().hex[:12], sql, hdrs)
        user = hdrs.get("x-trino-user", "user")
        group = self._group_for(hdrs)
        # per-group QPS quota on the fast path (round 14): every chain
        # level with a configured result_cache_qps must grant a token
        # BEFORE the hit is served; over quota answers QUERY_QUEUE_FULL
        # — the enforcement ROADMAP promised for the served_from_cache
        # accounting. Under a fleet, the check routes to the shared-
        # memory buckets (fast_path_quota) so the quota binds fleet-wide.
        if self.fast_path_quota is not None:
            allowed = self.fast_path_quota(group)
            if allowed:
                self.groups.record_cache_hit(group, enforce=False)
            else:
                # enforcement happened in the shared bucket; the group's
                # rejection counters must still move
                self.groups.record_cache_hit_rejection(group)
        else:
            allowed = self.groups.record_cache_hit(group) is not None
        if not allowed:
            q.state = "FAILED"
            q.error = protocol.error_json(
                f"Result-cache QPS quota exceeded for resource group "
                f"{group!r}", error_name="QUERY_QUEUE_FULL",
                error_code=131074, error_type="INSUFFICIENT_RESOURCES")
            q.info = TRACKER.begin(sql, user=user, query_id=qid,
                                   resource_group=group)
            TRACKER.fail(q.info, "Result-cache QPS quota exceeded",
                         error_name="QUERY_QUEUE_FULL")
            with self._lock:
                self._queries[qid] = q
                self._prune_locked()
            return q
        info = TRACKER.begin(sql, user=user, query_id=qid,
                             resource_group=group)
        q.info = info
        info.cpu_time_ms = 0
        info.output_bytes = entry.output_bytes
        # the delivery-mode-consistent stats contract: a hit reports the
        # SAME output rows/bytes a real run would with the zero-work
        # fields provably zero — built from a real collector snapshot so
        # the key set never drifts from obs/stats.py
        from trino_tpu.obs.stats import QueryStatsCollector
        col = QueryStatsCollector(qid)
        col.result_cache_hits = 1
        col.add_output(entry.row_count, entry.output_bytes)
        col.finish()
        stats = col.snapshot()
        stats["wall_s"] = 0.0
        info.stats = stats
        q.result = MaterializedResult(
            list(entry.column_names), list(entry.column_types),
            list(entry.rows), row_count=entry.row_count)
        # group accounting already happened at the quota gate above (the
        # fast path still skips submit/take/finish: a hit costs no
        # executor resources to admit)
        TRACKER.running(info)
        TRACKER.finish(info, entry.row_count)
        q.state = "FINISHED"
        with self._lock:
            self._queries[qid] = q
            self._prune_locked()
        return q

    def _prune_locked(self) -> None:
        """Bound the paging registry (QueryTracker expiry analog): drop
        the oldest terminal queries past `keep`, remembering their ids so
        a late GET answers 410 Gone instead of 404."""
        if len(self._queries) <= self.keep:
            return
        for qid in list(self._queries):
            if len(self._queries) <= self.keep:
                break
            if self._queries[qid].done:
                del self._queries[qid]
                self._pruned[qid] = None
        while len(self._pruned) > 5 * self.keep:
            self._pruned.pop(next(iter(self._pruned)))

    def _drain(self) -> None:
        """Executor-pool worker: block on the resource-group manager for
        the next weighted-fair pick, run it on a per-query runner clone;
        paging of finished queries proceeds on HTTP threads."""
        from trino_tpu.exec.query_tracker import TRACKER
        while not self._stopping.is_set():
            got = self.groups.take(timeout=0.2)
            if got is None:
                continue
            group, q = got
            slice_t0 = time.monotonic()
            try:
                if q.cancelled:
                    q.state = "CANCELED"
                    TRACKER.cancel(q.info)
                    continue
                q.state = "RUNNING"
                try:
                    self._execute(q)
                    if q.cancelled and q.result is None:
                        q.state = "CANCELED"
                    elif q.error is not None:
                        q.state = "FAILED"
                    elif q.stream is not None and q.stream.opened \
                            and not q.stream.drained \
                            and (q.result is None
                                 or len(q.result.rows)
                                 != q.result.reported_rows):
                        # producer done, ring still draining AND the ring
                        # is the only copy: paging flips it to FINISHED
                        # on the final chunk (with a complete
                        # materialized copy the buffered path serves and
                        # the query is simply FINISHED)
                        q.state = "FINISHING"
                    else:
                        q.state = "FINISHED"
                except BaseException as e:  # noqa: BLE001 — keep draining
                    q.error = protocol.error_from_exception(e)
                    q.state = "FAILED"
                    self._fail_tracker(q, e)
            finally:
                # weighted CPU scheduling: this slice's wall charges to
                # the group chain (stride advances by seconds/weight),
                # so the next pick favors groups that consumed less
                # executor time per unit weight
                self.groups.charge(group, time.monotonic() - slice_t0,
                                   query_id=q.query_id)
                self.groups.finish(group, q.query_id)

    @staticmethod
    def _fail_tracker(q: _Query, exc: BaseException) -> None:
        """Transition the pre-registered tracker entry when a failure
        happens OUTSIDE runner.execute() (e.g. a malformed session
        property raising at set() time): without this the entry stays
        QUEUED forever — a phantom row in system.runtime.queries that
        pruning (terminal-only) never removes, and no query_failed
        event/metrics ever fire."""
        from trino_tpu.errors import classify
        from trino_tpu.exec.query_tracker import TERMINAL, TRACKER
        info = q.info
        if info is None or info.state in TERMINAL:
            return
        try:
            TRACKER.fail(info, f"{type(exc).__name__}: {exc}",
                         error_name=classify(exc).name)
        except ValueError:
            pass    # lost the race to a concurrent terminal transition

    @staticmethod
    def _apply_prepared_header(runner, headers: dict) -> None:
        """X-Trino-Prepared-Statement: comma-separated name=value pairs,
        both URL-encoded, each value a statement's SQL — the stateless
        client re-sends every prepared statement per request
        (ProtocolHeaders.requestPreparedStatement). Applied to a PRIVATE
        overlay of the runner's prepared map, so concurrent clients'
        names never collide server-side."""
        from urllib.parse import unquote
        from trino_tpu.sql import parse_statement
        # overlay even when the header is absent: a PREPARE executed by
        # this query must not leak into the shared base map (the client
        # gets it back via X-Trino-Added-Prepare instead)
        runner._prepared = dict(runner._prepared)
        header = headers.get("x-trino-prepared-statement", "")
        for part in header.split(","):
            if "=" not in part:
                continue
            name, _, enc = part.partition("=")
            runner._prepared[unquote(name.strip())] = \
                parse_statement(unquote(enc.strip()))

    def _execute(self, q: _Query) -> None:
        headers = q.headers
        # per-query runner clone: a PRIVATE session over the shared
        # catalogs, so concurrent executors never cross-contaminate
        # session state (the protocol is stateless — the
        # X-Trino-Set-Session response header hands SET SESSION state
        # back to THIS client, which re-sends it via X-Trino-Session)
        runner = self.runner.for_query()
        session = runner.session
        sink = None
        if self.streaming_enabled:
            # the runner opens it only for streaming-safe shapes (plain
            # reads under retry_policy=NONE without chaos); unopened, the
            # paging path falls back to the buffered result
            sink = ResultStream(
                max_chunks=self.stream_ring_chunks,
                chunk_rows=PAGE_ROWS,
                stall_timeout_s=self.stream_stall_timeout_s)
            q.stream = sink
        try:
            catalog = headers.get("x-trino-catalog")
            schema = headers.get("x-trino-schema")
            if catalog:
                session.catalog = catalog
            if schema:
                session.schema = schema
            from trino_tpu.metadata import SESSION_PROPERTY_DEFAULTS
            for k, v in self._session_overrides(headers).items():
                if k not in SESSION_PROPERTY_DEFAULTS:
                    continue    # tolerate properties this engine lacks
                # a KNOWN property with a malformed value fails the query
                # (set() coerces to the default's type at SET time) — the
                # pre-coercion contract, where the raw string failed at
                # execute(), kept the same visibility
                session.set(k, v)
            self._apply_prepared_header(runner, headers)
            # the runner builds the query's deadline AFTER the session
            # overrides apply (so header-sent limits bind), from the
            # submit time (query_max_run_time counts queueing) capped
            # by the server's per-query wall-clock limit, and adopts
            # q.cancel_event so DELETE cancels cooperatively
            result = runner.execute(
                q.sql, query_id=q.query_id, queued_at=q.started,
                wall_cap_s=self.query_timeout_s,
                cancel_event=q.cancel_event, result_sink=sink)
            m = _SET_SESSION.match(q.sql)
            if m:
                q.update_type = "SET SESSION"
                q.set_session = (m.group(1),
                                 m.group(2).strip().strip("'"))
            m = _RESET_SESSION.match(q.sql)
            if m:
                q.update_type = "RESET SESSION"
                q.clear_session = m.group(1)
            m = _PREPARE.match(q.sql)
            if m:
                q.update_type = "PREPARE"
                # echo the PARSER-normalized name (unquoted identifiers
                # lowercase, quoted verbatim): the stateless client
                # re-sends this key per request and EXECUTE resolves
                # names through the parser again, so echoing the raw
                # capture would install a key EXECUTE can never find.
                # The statement text rides from the regex (the AST can't
                # be un-parsed back to SQL).
                from trino_tpu.sql import parse_statement
                q.added_prepare = (parse_statement(q.sql).name.value,
                                   m.group(2).strip())
            m = _DEALLOCATE.match(q.sql)
            if m:
                q.update_type = "DEALLOCATE"
                from trino_tpu.sql import parse_statement
                q.deallocated_prepare = parse_statement(q.sql).name.value
            # publish LAST: a concurrently-polling client that sees
            # q.result must also see update_type/set_session (else the
            # X-Trino-Set-Session header is lost)
            q.result = result
            if sink is not None:
                sink.close()    # producer done; ring drains to the client
        except QueryCanceledError as e:
            q.cancelled = True         # surfaces as CANCELED, not FAILED
            if sink is not None:
                sink.fail(e)           # wake a blocked consumer
        except Exception as e:  # surface as QueryError, not HTTP 500
            q.error = protocol.error_from_exception(e)
            if sink is not None:
                sink.fail(e)
            # failures BEFORE runner.execute() (session-override coercion)
            # must still terminate the tracker entry; inside execute() the
            # runner already transitioned it (this is then a no-op)
            self._fail_tracker(q, e)

    # ----------------------------------------------------- query REST API

    @staticmethod
    def _query_info_payload(qid: str) -> Optional[dict]:
        """GET /v1/query/{id} (QueryResource.getQueryInfo analog): the
        live tracker entry while it exists, the history-ring record
        after pruning — a just-finished query's stats stay queryable
        past the tracker's retention bound."""
        from trino_tpu.exec.query_tracker import TRACKER
        from trino_tpu.obs.history import HISTORY, record_from_info
        for info in TRACKER.list():
            if info.query_id == qid:
                # the SAME record shape the history branch serves (one
                # builder — a consumer must never see fields flicker in
                # and out with prune timing), plus the live-only extras
                from trino_tpu.exec.query_tracker import TERMINAL
                rec = record_from_info(info)
                payload = TrinoServer._record_payload(rec, "tracker")
                if info.state not in TERMINAL:
                    payload["endedAt"] = None   # still executing
                return payload
        entry = HISTORY.get(qid)
        if entry is None:
            return None
        return TrinoServer._record_payload(entry, "history")

    @staticmethod
    def _record_payload(rec, source: str) -> dict:
        return {
            "queryId": rec.query_id, "state": rec.state,
            "user": rec.user, "query": rec.query,
            "rows": rec.rows, "outputBytes": rec.output_bytes,
            "wallMillis": rec.wall_ms,
            "cpuTimeMillis": rec.cpu_time_ms,
            "deviceTimeMillis": rec.device_time_ms,
            "compileTimeMillis": rec.compile_time_ms,
            "error": rec.error, "errorName": rec.error_name,
            "errorType": rec.error_type, "retryable": rec.retryable,
            "retries": rec.retries,
            "resourceGroup": rec.resource_group,
            "peakMemoryBytes": rec.peak_memory_bytes,
            "stats": rec.stats, "endedAt": rec.ended_at,
            "traceFile": rec.trace_file,
            "source": source,
        }

    @staticmethod
    def _query_trace_payload(qid: str) -> Optional[dict]:
        """GET /v1/query/{id}/trace: the query's span tree as
        Chrome-trace JSON (generated on demand — works whether or not
        the session exported a trace file), served from the live
        tracker or the history ring."""
        from trino_tpu.exec.query_tracker import TRACKER
        from trino_tpu.obs.spans import to_chrome_trace
        trace = None
        for info in TRACKER.list():
            if info.query_id == qid:
                trace = info.trace
                break
        if trace is None:
            from trino_tpu.obs.history import HISTORY
            entry = HISTORY.get(qid)
            if entry is not None:
                trace = entry.trace
        if trace is None:
            return None
        return to_chrome_trace(trace, qid)

    # ------------------------------------------------------------ paging

    def _page_uri(self, q: _Query, token: int) -> str:
        return (f"{self.base_uri}/v1/statement/executing/"
                f"{q.query_id}/{q.slug}/{token}")

    def _warnings_for(self, q: _Query) -> list:
        info = q.info
        if info is None or not info.warnings:
            return []
        return [protocol.warning_json(w) for w in info.warnings]

    def _response_for(self, q: _Query, token: int) -> dict:
        info = q.info
        # live while RUNNING (info.mem is the executing ledger), final
        # after close (info.pool_peak_bytes)
        peak = 0
        if info is not None:
            peak = max(info.pool_peak_bytes,
                       info.mem.peak if info.mem is not None else 0)
        if q.error is not None:
            return protocol.query_results(
                q.query_id, self.base_uri, state="FAILED", error=q.error,
                elapsed_ms=q.elapsed_ms, peak_memory_bytes=peak,
                warnings=self._warnings_for(q))
        # a materialized result outranks a cancel flag: the query beat the
        # cancel to the finish line, so its buffered pages stay servable
        # (the reference treats cancel of a terminal query as a no-op)
        if q.cancelled and q.result is None:
            return protocol.query_results(
                q.query_id, self.base_uri, state="CANCELED",
                error=protocol.error_json(
                    "Query was canceled", error_name="USER_CANCELED",
                    error_code=3, error_type="USER_ERROR"),
                elapsed_ms=q.elapsed_ms)
        stream = q.stream
        res = q.result
        if stream is not None and stream.opened and (
                res is None or len(res.rows) != res.reported_rows):
            # ring-only delivery: while executing (res is None) and for
            # results whose materialized copy was dropped past the cache
            # bound. Once a COMPLETE copy exists, the buffered path below
            # serves instead — its 1000-row pages are chunk-identical to
            # the ring's, and stay re-readable after the ring drains
            # (the pre-streaming paging contract)
            return self._stream_response(q, stream, token, info, peak)
        if q.result is None:
            # still queued/running: same token again (client poll loop)
            return protocol.query_results(
                q.query_id, self.base_uri,
                next_uri=self._page_uri(q, token), state=q.state,
                elapsed_ms=q.elapsed_ms, peak_memory_bytes=peak)
        res = q.result
        cols = protocol.columns_json(res.column_names, res.column_types)
        lo, hi = token * PAGE_ROWS, (token + 1) * PAGE_ROWS
        chunk = res.rows[lo:hi]
        data = protocol.encode_rows(chunk, res.column_types)
        has_more = hi < len(res.rows)
        spilled = 0
        if info is not None and info.stats:
            spilled = int(info.stats.get("spilled_bytes", 0))
        return protocol.query_results(
            q.query_id, self.base_uri, columns=cols, data=data,
            next_uri=self._page_uri(q, token + 1) if has_more else None,
            state="RUNNING" if has_more else "FINISHED",
            update_type=q.update_type, rows=len(res.rows),
            elapsed_ms=q.elapsed_ms, peak_memory_bytes=peak,
            cpu_time_ms=info.cpu_time_ms if info is not None else None,
            processed_bytes=info.output_bytes if info is not None else 0,
            spilled_bytes=spilled,
            warnings=self._warnings_for(q))

    def _stream_response(self, q: _Query, stream: ResultStream,
                         token: int, info, peak: int) -> dict:
        """Incremental paging off the result ring: chunk `token` is
        served the moment the producer writes it — the client's first
        page arrives while the query is still RUNNING. A 'pending' get
        (the producer hasn't reached this chunk yet) answers the SAME
        token so the client polls; 'end' closes the protocol
        (FINISHED, no nextUri, final stats)."""
        status, chunk = stream.get(token, timeout=0.2)
        cols = protocol.columns_json(stream.column_names,
                                     stream.column_types)
        state = q.state if q.state in ("RUNNING", "FINISHING") \
            else "RUNNING"
        if status == "error":
            exc = stream.error
            if isinstance(exc, QueryCanceledError) or q.cancelled:
                return protocol.query_results(
                    q.query_id, self.base_uri, state="CANCELED",
                    error=protocol.error_json(
                        "Query was canceled", error_name="USER_CANCELED",
                        error_code=3, error_type="USER_ERROR"),
                    elapsed_ms=q.elapsed_ms)
            return protocol.query_results(
                q.query_id, self.base_uri, state="FAILED",
                error=q.error or protocol.error_from_exception(exc),
                elapsed_ms=q.elapsed_ms, peak_memory_bytes=peak,
                warnings=self._warnings_for(q))
        if status == "gone":
            # behind the ack horizon: the client advanced past this
            # token, then came back — unservable, like a pruned query
            return protocol.query_results(
                q.query_id, self.base_uri, state="FAILED",
                error=protocol.error_json(
                    f"result page {token} was already consumed",
                    error_name="PAGE_TRANSPORT_ERROR", error_code=65545,
                    error_type="INTERNAL_ERROR"),
                elapsed_ms=q.elapsed_ms)
        if status == "pending":
            return protocol.query_results(
                q.query_id, self.base_uri, columns=cols,
                next_uri=self._page_uri(q, token), state=state,
                elapsed_ms=q.elapsed_ms, peak_memory_bytes=peak)
        spilled = 0
        cpu_ms = None
        nbytes = 0
        if info is not None and info.stats:
            spilled = int(info.stats.get("spilled_bytes", 0))
            cpu_ms = info.cpu_time_ms
            nbytes = info.output_bytes
        if status == "end":
            q.state = "FINISHED"
            return protocol.query_results(
                q.query_id, self.base_uri, columns=cols,
                state="FINISHED", update_type=q.update_type,
                rows=stream.total_rows, elapsed_ms=q.elapsed_ms,
                peak_memory_bytes=peak, cpu_time_ms=cpu_ms,
                processed_bytes=nbytes, spilled_bytes=spilled,
                warnings=self._warnings_for(q))
        data = protocol.encode_rows(chunk, stream.column_types)
        return protocol.query_results(
            q.query_id, self.base_uri, columns=cols, data=data,
            next_uri=self._page_uri(q, token + 1), state=state,
            rows=stream.total_rows, elapsed_ms=q.elapsed_ms,
            peak_memory_bytes=peak, cpu_time_ms=cpu_ms,
            processed_bytes=nbytes, spilled_bytes=spilled,
            warnings=self._warnings_for(q))

    # ----------------------------------------------------------- handler

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _send_json(self, payload: dict, q: Optional[_Query] = None,
                           status: int = 200):
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if q is not None and q.set_session is not None:
                    from urllib.parse import quote
                    k, v = q.set_session
                    self.send_header("X-Trino-Set-Session",
                                     f"{k}={quote(str(v))}")
                if q is not None and q.clear_session is not None:
                    self.send_header("X-Trino-Clear-Session",
                                     q.clear_session)
                if q is not None and q.added_prepare is not None:
                    from urllib.parse import quote
                    name, stmt_sql = q.added_prepare
                    self.send_header(
                        "X-Trino-Added-Prepare",
                        f"{quote(name, safe='')}="
                        f"{quote(stmt_sql, safe='')}")
                if q is not None and q.deallocated_prepare is not None:
                    from urllib.parse import quote
                    self.send_header("X-Trino-Deallocated-Prepare",
                                     quote(q.deallocated_prepare,
                                           safe=""))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                if self.path.rstrip("/") != "/v1/statement":
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                sql = self.rfile.read(length).decode()
                if server.draining.is_set():
                    # drain protocol: no NEW statements; in-flight
                    # queries and open streams keep paging below
                    self._send_json(protocol.query_results(
                        "draining", server.base_uri, state="FAILED",
                        error=protocol.error_json(
                            "Server is shutting down",
                            error_name="SERVER_SHUTTING_DOWN",
                            error_code=131075,
                            error_type="INSUFFICIENT_RESOURCES")))
                    return
                # group-config hot-reload check rides the submit path
                # (throttled): an edited JSON file re-applies here
                server._maybe_reload_groups()
                # result-cache fast path: a hit answers FINISHED right
                # here — data inline when it fits the first page, else
                # paged off q.result — without touching the dispatcher
                q = server._try_cached(sql, self.headers)
                if q is not None:
                    self._send_json(server._response_for(q, 0), q)
                    return
                q = server._submit(sql, self.headers)
                # first response: QUEUED with a nextUri (the dispatcher
                # handshake the CLI expects), data starts at token 0
                if q.error is not None:
                    self._send_json(server._response_for(q, 0), q)
                    return
                self._send_json(protocol.query_results(
                    q.query_id, server.base_uri,
                    next_uri=server._page_uri(q, 0), state="QUEUED",
                    elapsed_ms=q.elapsed_ms), q)

            def do_GET(self):
                parts = self.path.strip("/").split("/")
                if len(parts) >= 3 and parts[:2] == ["v1", "query"]:
                    # /v1/query/{id} + /v1/query/{id}/trace: query info
                    # and Chrome-trace export, live or from history
                    qid = parts[2]
                    if len(parts) == 4 and parts[3] == "trace":
                        payload = server._query_trace_payload(qid)
                    elif len(parts) == 3:
                        payload = server._query_info_payload(qid)
                    else:
                        payload = None
                    if payload is None:
                        self.send_error(404, "Query not found")
                        return
                    self._send_json(payload)
                    return
                if self.path.rstrip("/") == "/v1/metrics":
                    # Prometheus scrape endpoint (the jmx-prometheus
                    # agent surface of a reference deployment, native)
                    from trino_tpu.obs.metrics import REGISTRY
                    body = REGISTRY.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                q, token = self._resolve()
                if q is None:
                    return
                self._send_json(server._response_for(q, token), q)

            def do_DELETE(self):
                q, _ = self._resolve()
                if q is None:
                    return
                if not q.done:
                    # cancel of a terminal query is a no-op (reference
                    # semantics); otherwise the runner observes the
                    # event at its next cooperative checkpoint — no
                    # current-query bookkeeping race: if the executor
                    # picks this query up LATER, the already-set event
                    # cancels it at its first checkpoint
                    q.cancelled = True
                    # CancelEvent.cancel() stamps the DELETE time with
                    # the set: the runner's deadline reads it to report
                    # `preempt_latency_ms` (DELETE -> unwind, the
                    # slice-bounded cancellation wall)
                    q.cancel_event.cancel()
                self.send_response(204)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def _resolve(self):
                parts = self.path.strip("/").split("/")
                # v1/statement/executing/{id}/{slug}/{token}
                if len(parts) != 6 or parts[:3] != ["v1", "statement",
                                                    "executing"]:
                    self.send_error(404)
                    return None, 0
                qid, slug, token_str = parts[3], parts[4], parts[5]
                with server._lock:
                    q = server._queries.get(qid)
                    purged = qid in server._pruned
                if q is None:
                    if purged:
                        # the query existed but its results were pruned:
                        # 410 tells the client retrying is pointless
                        self.send_error(410, "Query results purged")
                    else:
                        self.send_error(404, "Query not found")
                    return None, 0
                if q.slug != slug:
                    self.send_error(404, "Query not found")
                    return None, 0
                try:
                    token = int(token_str)
                except ValueError:
                    self.send_error(404, "Invalid page token")
                    return None, 0
                if token < 0:
                    self.send_error(404, "Invalid page token")
                    return None, 0
                return q, token

        return Handler
