"""system catalog: runtime introspection tables.

Reference parity: core/trino-main connector/system/ —
system.runtime.{queries,tasks,nodes} backed by live engine state
(GlobalSystemConnector + QuerySystemTable/TaskSystemTable/NodeSystemTable).
Tables materialize a snapshot page at scan time from the process-wide
QueryTracker and the JAX device topology (the node inventory of a
single-controller TPU engine is its device list, not a discovery service).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from trino_tpu import types as T
from trino_tpu.connector.spi import (
    ColumnHandle, ColumnMetadata, Connector, ConnectorMetadata,
    ConnectorPageSource, ConnectorSplitManager, ConnectorTableHandle,
    SchemaTableName, Split, TableMetadata, TableStatistics)
from trino_tpu.page import Column, Dictionary, Page

TABLES: Dict[str, tuple] = {
    "queries": (
        ("query_id", T.VarcharType()), ("state", T.VarcharType()),
        ("user", T.VarcharType()), ("query", T.VarcharType()),
        ("rows", T.BIGINT), ("bytes", T.BIGINT),
        ("wall_ms", T.BIGINT), ("cpu_time_ms", T.BIGINT),
        ("error", T.VarcharType()), ("error_name", T.VarcharType()),
        ("retries", T.BIGINT), ("faults_injected", T.BIGINT),
        ("resource_group", T.VarcharType()),
        ("pool_reserved_bytes", T.BIGINT), ("pool_peak_bytes", T.BIGINT),
        ("memory_kills", T.BIGINT), ("leaked_bytes", T.BIGINT),
        ("spilled_bytes", T.BIGINT),
        ("device_time_ms", T.DOUBLE), ("compile_time_ms", T.DOUBLE)),
    # the query-history ring (obs/history.py): terminal queries retained
    # past the live tracker's pruning bound, with the device/compile/host
    # time split and the full error taxonomy — the post-incident table
    "completed_queries": (
        ("query_id", T.VarcharType()), ("state", T.VarcharType()),
        ("user", T.VarcharType()), ("query", T.VarcharType()),
        ("rows", T.BIGINT), ("bytes", T.BIGINT),
        ("wall_ms", T.BIGINT), ("cpu_time_ms", T.BIGINT),
        ("device_time_ms", T.DOUBLE), ("compile_time_ms", T.DOUBLE),
        ("error", T.VarcharType()), ("error_name", T.VarcharType()),
        ("error_type", T.VarcharType()), ("retryable", T.BOOLEAN),
        ("retries", T.BIGINT), ("faults_injected", T.BIGINT),
        ("resource_group", T.VarcharType()),
        ("peak_memory_bytes", T.BIGINT), ("ended_at_ms", T.BIGINT)),
    "tasks": (
        ("query_id", T.VarcharType()), ("task_id", T.VarcharType()),
        ("state", T.VarcharType()), ("rows", T.BIGINT),
        ("wall_ms", T.BIGINT)),
    "nodes": (
        ("node_id", T.VarcharType()), ("node_version", T.VarcharType()),
        ("coordinator", T.BOOLEAN), ("state", T.VarcharType()),
        ("pool_limit_bytes", T.BIGINT), ("pool_reserved_bytes", T.BIGINT),
        ("pool_peak_bytes", T.BIGINT), ("pool_kills", T.BIGINT),
        ("pool_leaks", T.BIGINT), ("pool_leaked_bytes", T.BIGINT),
        ("pool_budget_source", T.VarcharType()),
        ("device_reserved_bytes", T.BIGINT),
        ("device_peak_bytes", T.BIGINT)),
    "resource_groups": (
        ("name", T.VarcharType()), ("parent", T.VarcharType()),
        ("queued", T.BIGINT), ("running", T.BIGINT),
        ("started", T.BIGINT), ("finished", T.BIGINT),
        ("served_from_cache", T.BIGINT),
        ("cache_hit_rejections", T.BIGINT),
        ("result_cache_qps", T.DOUBLE),
        ("hard_concurrency", T.BIGINT), ("max_queued", T.BIGINT),
        ("soft_memory_limit_bytes", T.BIGINT),
        ("scheduling_weight", T.BIGINT),
        ("memory_usage_bytes", T.BIGINT),
        ("scheduled_wall_ms", T.BIGINT)),
    # the serving tier's cache inventory (trino_tpu/serve/caches.py +
    # exec/plan_cache.py + exec/jit_cache.py): one row per cache layer,
    # the same counters /v1/metrics exports, SQL-queryable
    "caches": (
        ("cache", T.VarcharType()), ("entries", T.BIGINT),
        ("bytes", T.BIGINT), ("hits", T.BIGINT), ("misses", T.BIGINT),
        ("evictions", T.BIGINT), ("invalidations", T.BIGINT)),
    # the process metrics registry (obs/metrics.py) as a table: the same
    # samples GET /v1/metrics exposes, SQL-queryable
    "metrics": (
        ("name", T.VarcharType()), ("kind", T.VarcharType()),
        ("labels", T.VarcharType()), ("value", T.DOUBLE)),
    # deployment-level server/fleet knobs (metadata.SERVER_PROPERTY_DOCS):
    # constructor properties, not session properties — surfaced so
    # operators can discover them the same way they discover session
    # properties through SHOW SESSION
    "server_properties": (
        ("name", T.VarcharType()), ("description", T.VarcharType())),
    # the MV registry (trino_tpu/mv/): one row per materialized view
    # across live runners — definition freshness (seconds of unfolded
    # base history), the recorded base versions of the last refresh,
    # and the refresh/rewrite/republish counters behind trino_tpu_mv_*
    "materialized_views": (
        ("catalog", T.VarcharType()), ("schema", T.VarcharType()),
        ("name", T.VarcharType()), ("storage_table", T.VarcharType()),
        ("incremental", T.BOOLEAN), ("refreshed_at", T.DOUBLE),
        ("staleness_s", T.DOUBLE), ("base_versions", T.VarcharType()),
        ("refreshes_delta", T.BIGINT), ("refreshes_full", T.BIGINT),
        ("rewrite_hits", T.BIGINT), ("republished", T.BIGINT)),
}


def _rows_for(table: str) -> List[tuple]:
    from trino_tpu.exec.query_tracker import TRACKER
    if table == "queries":
        return [(q.query_id, q.state, q.user, q.query, q.rows,
                 q.output_bytes,
                 q.wall_ms if q.wall_ms is not None else 0,
                 q.cpu_time_ms, q.error,
                 q.error_name, q.retries, q.faults_injected,
                 q.resource_group, q.pool_reserved_bytes,
                 max(q.pool_peak_bytes,
                     q.mem.peak if q.mem is not None else 0),
                 max(q.memory_kills,
                     q.mem.kills if q.mem is not None else 0),
                 q.leaked_bytes,
                 (q.stats or {}).get("spilled_bytes", 0),
                 float((q.stats or {}).get("device_time_ms", 0) or 0),
                 float((q.stats or {}).get("compile_time_ms", 0) or 0))
                for q in TRACKER.list()]
    if table == "completed_queries":
        from trino_tpu.obs.history import HISTORY
        return [(c.query_id, c.state, c.user, c.query, c.rows,
                 c.output_bytes, c.wall_ms, c.cpu_time_ms,
                 c.device_time_ms, c.compile_time_ms, c.error,
                 c.error_name, c.error_type,
                 bool(c.retryable) if c.retryable is not None else None,
                 c.retries, c.faults_injected, c.resource_group,
                 c.peak_memory_bytes, int(c.ended_at * 1000))
                for c in HISTORY.list()]
    if table == "tasks":
        # single-controller engine: one task per query (the mesh's shards
        # are lanes inside one program, not separately tracked tasks)
        return [(q.query_id, f"{q.query_id}.0.0", q.state, q.rows,
                 q.wall_ms if q.wall_ms is not None else 0)
                for q in TRACKER.list()]
    if table == "nodes":
        import jax

        from trino_tpu.exec.memory import NODE_POOL
        try:
            devices = jax.devices()
        except Exception:
            devices = []
        # the pool columns repeat per device row (the node pool is the
        # single-controller process's per-chip budget + source); the
        # device_* columns are THAT chip's attributed reservations, fed
        # by mesh shard executors and sharded staging
        pool = (NODE_POOL.limit or 0, NODE_POOL.reserved, NODE_POOL.peak,
                NODE_POOL.kills, NODE_POOL.leaks, NODE_POOL.leaked_bytes,
                NODE_POOL.budget_source)
        return [(f"{d.platform}-{d.id}", jax.__version__, d.id == 0,
                 "active") + pool
                + (NODE_POOL.device_reserved.get(i, 0),
                   NODE_POOL.device_peak.get(i, 0))
                for i, d in enumerate(devices)]
    if table == "resource_groups":
        from trino_tpu.exec.resource_groups import list_all_groups
        return [(g.name,
                 g.parent.name if g.parent is not None else None,
                 g.queued, len(g.running), g.started, g.finished,
                 g.served_from_cache,
                 g.cache_hit_rejections,
                 g.result_cache_qps if g.result_cache_qps is not None
                 else 0.0,
                 g.hard_concurrency, g.max_queued,
                 g.soft_memory_limit_bytes if
                 g.soft_memory_limit_bytes is not None else 0,
                 g.weight, g.memory_usage(),
                 int(g.scheduled_wall_s * 1000))
                for g in list_all_groups()]
    if table == "caches":
        from trino_tpu.exec import jit_cache, plan_cache
        from trino_tpu.exec.table_cache import table_cache_stats
        from trino_tpu.serve.caches import (result_cache_stats,
                                            scan_cache_stats)
        ps = plan_cache.stats()
        rs = result_cache_stats()
        ss = scan_cache_stats()
        ts = table_cache_stats()
        js = jit_cache.stats()
        return [
            ("plan", ps["entries"], 0, ps["hits"], ps["misses"],
             ps["evictions"], ps["invalidations"]),
            ("result", rs["entries"], 0, rs["hits"], rs["misses"],
             rs["evictions"], rs["invalidations"]),
            ("scan", ss["entries"], ss["bytes"], ss["hits"],
             ss["misses"], ss["evictions"], ss["invalidations"]),
            ("table", ts["entries"], ts["bytes"], ts["hits"],
             ts["misses"], ts["evictions"], ts["invalidations"]),
            ("jit", js["size"], 0, js["hits"], js["misses"],
             js["evictions"], 0),
        ]
    if table == "metrics":
        from trino_tpu.obs.metrics import REGISTRY
        return REGISTRY.samples()
    if table == "server_properties":
        from trino_tpu.metadata import SERVER_PROPERTY_DOCS
        return sorted(SERVER_PROPERTY_DOCS.items())
    if table == "materialized_views":
        from trino_tpu.mv.manager import all_materialized_view_rows
        return all_materialized_view_rows()
    raise KeyError(table)


class SystemMetadata(ConnectorMetadata):
    def list_schemas(self) -> List[str]:
        return ["runtime"]

    def list_tables(self, schema: Optional[str] = None
                    ) -> List[SchemaTableName]:
        return [SchemaTableName("runtime", t) for t in sorted(TABLES)]

    def get_table_handle(self, name: SchemaTableName
                         ) -> Optional[ConnectorTableHandle]:
        if name.schema == "runtime" and name.table in TABLES:
            return ConnectorTableHandle(name)
        return None

    def get_table_metadata(self, handle: ConnectorTableHandle
                           ) -> TableMetadata:
        cols = tuple(ColumnMetadata(n, ty)
                     for n, ty in TABLES[handle.name.table])
        return TableMetadata(handle.name, cols)

    def get_table_statistics(self, handle: ConnectorTableHandle
                             ) -> TableStatistics:
        return TableStatistics(float(len(_rows_for(handle.name.table))))


class SystemSplitManager(ConnectorSplitManager):
    def get_splits(self, handle: ConnectorTableHandle,
                   target_splits: int = 1) -> List[Split]:
        return [Split(handle, 0, 1, host=0)]


class SystemPageSource(ConnectorPageSource):
    def pages(self, split: Split, columns: Sequence[ColumnHandle],
              page_capacity: int) -> Iterator[Page]:
        table = split.table.name.table
        rows = _rows_for(table)
        n = len(rows)
        cap = max(8, 1 << max(3, (n - 1).bit_length()) if n else 8)
        cols = []
        spec = TABLES[table]
        for ch in columns:
            pos = next(i for i, (nm, _) in enumerate(spec) if nm == ch.name)
            vals = [r[pos] for r in rows]
            if T.is_string(ch.type):
                d, codes = Dictionary.build(np.asarray(
                    [v if v is not None else "" for v in vals] or [""],
                    dtype=object))
                arr = np.zeros(cap, dtype=np.int32)
                arr[:n] = codes[:n]
                valid = None
                if any(v is None for v in vals):
                    va = np.zeros(cap, dtype=bool)
                    va[:n] = [v is not None for v in vals]
                    valid = va
                cols.append(Column.from_numpy(arr, ch.type, valid=valid,
                                              dictionary=d))
            else:
                dt = T.to_numpy_dtype(ch.type)
                arr = np.zeros(cap, dtype=dt)
                arr[:n] = [0 if v is None else v for v in vals]
                cols.append(Column.from_numpy(arr, ch.type))
        yield Page(tuple(cols), n)


def create_connector() -> Connector:
    return Connector("system", SystemMetadata(), SystemSplitManager(),
                     SystemPageSource())
