"""Operator library: the compute kernels of the engine.

Reference parity: core/trino-main/.../operator/ (SURVEY §2.7). Operators here
are pure functions Page -> Page built from static parameters; a plan fragment
composes them into one function that jits into a single fused XLA program —
the Driver/Operator pull loop (operator/Driver.java:355) collapses into XLA's
own scheduling, which is the TPU-idiomatic replacement for pipeline
parallelism across operators.
"""

from trino_tpu.ops.filter_project import filter_project
from trino_tpu.ops.aggregate import (
    AGGREGATES, AggSpec, hash_aggregate, Step)
from trino_tpu.ops.join import hash_join, prepare_build, JoinType
from trino_tpu.ops.sort import (limit, order_by, top_n, top_n_masked,
                                SortKey)
