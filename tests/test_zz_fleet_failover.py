"""Engine failover (ISSUE 16): supervised crash recovery, worker
degraded-mode serving, zero-drop planned engine restart over SCM_RIGHTS.

The acceptance suite for the supervised-engine topology: the engine is
a SUBPROCESS the FleetSupervisor monitors; kill -9 mid-stream must leave
shared-tier HITS serving uninterrupted, classify misses as the
retryable ENGINE_UNAVAILABLE taxonomy (never a raw connection reset),
and restore a rehydrated engine generation (prepared statements, warm
caches) without a single stale shm read. The planned path proves the
stronger claim: `engine_restart()` swaps generations by passing the
live dispatch listener over SCM_RIGHTS, so a closed loop of cache
MISSES sees zero errors across the swap.

Named test_zz_* so these process-chaos sweeps collect LAST (the tier-1
wall budget spends on the seed suites first)."""

import json
import os
import signal
import socket
import threading
import time
import urllib.request

import pytest

pytestmark = pytest.mark.skipif(
    not hasattr(socket, "SO_REUSEPORT"),
    reason="fleet serving needs SO_REUSEPORT")


# ------------------------------------------------------------ unit layer


def test_circuit_breaker_state_machine():
    from trino_tpu.fleet.worker import CircuitBreaker
    br = CircuitBreaker(failure_threshold=3, reset_s=0.2)
    assert br.state == 0 and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == 0 and br.allow()      # under threshold: CLOSED
    br.record_failure()
    assert br.state == 2 and not br.allow()  # threshold consecutive: OPEN
    time.sleep(0.25)
    assert br.allow()                        # one HALF_OPEN trial
    assert br.state == 1
    assert not br.allow()                    # others fast-fail mid-trial
    br.record_failure()                      # trial failed: straight back
    assert br.state == 2 and not br.allow()
    time.sleep(0.25)
    assert br.allow()
    br.record_success()                      # trial succeeded: CLOSED
    assert br.state == 0 and br.allow()
    # a success resets the consecutive-failure count entirely
    br.record_failure()
    br.record_failure()
    br.record_success()
    br.record_failure()
    br.record_failure()
    assert br.state == 0
    # reset() is the engine_epoch bus notice's hammer
    br.record_failure()
    assert br.state == 2
    br.reset()
    assert br.state == 0 and br.allow()


def test_scm_rights_handoff_roundtrip(tmp_path):
    """A LISTENING socket fd crosses a unix socket via SCM_RIGHTS and
    keeps accepting on the other side — the mechanism under
    engine_restart()'s zero-drop swap."""
    from trino_tpu.fleet.handoff import HandoffListener, offer_fds
    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(8)
    port = lsock.getsockname()[1]
    path = str(tmp_path / "handoff.sock")
    listener = HandoffListener(path)
    meta_sent = {"port": port, "epoch": 7}

    def _offer():
        offer_fds(path, [lsock.fileno()], meta_sent, timeout_s=10)

    th = threading.Thread(target=_offer, daemon=True)
    th.start()
    fds, meta = listener.accept_fds(timeout_s=10)
    th.join(timeout=10)
    listener.close()
    assert meta == meta_sent and len(fds) == 1
    # a connection initiated BEFORE the original fd closes is accepted
    # through the passed fd (the kernel backlog carries the gap)
    client = socket.create_connection(("127.0.0.1", port), timeout=5)
    lsock.close()       # old generation exits
    adopted = socket.socket(fileno=fds[0])
    adopted.settimeout(5)
    conn, _ = adopted.accept()
    client.sendall(b"ping")
    assert conn.recv(4) == b"ping"
    conn.close()
    client.close()
    adopted.close()


def test_bus_drops_counted_and_logged_once(tmp_path, capfd):
    from trino_tpu.fleet.bus import FleetBus
    bus = FleetBus(str(tmp_path), "solo")
    try:
        # a member that vanished without unbinding: every send drops
        dead = os.path.join(str(tmp_path), "bus", "ghost.sock")
        with open(dead, "w"):
            pass
        assert not bus.send_to("ghost", {"kind": "hits", "n": 1})
        assert not bus.send_to("ghost", {"kind": "hits", "n": 2})
        assert not bus.send_to("ghost", {"kind": "prepare", "name": "x"})
        # oversize datagrams drop under their own kind
        bus.publish({"kind": "hits", "pad": "x" * 70000})
        drops = bus.drops_snapshot()
        assert drops["hits"] == 3
        assert drops["prepare"] == 1
        err = capfd.readouterr().err
        assert err.count("dropped 'hits' datagram") == 1     # once per kind
        assert err.count("dropped 'prepare' datagram") == 1
    finally:
        bus.close()


# ------------------------------------------------- the fleet, end to end


FAILOVER_RG = {"groups": [{"name": "global"}]}


def _http(base, sql, headers=None, timeout=30):
    req = urllib.request.Request(f"{base}/v1/statement",
                                 data=sql.encode(), method="POST")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    resp = urllib.request.urlopen(req, timeout=timeout)
    payload = json.loads(resp.read())
    rows = list(payload.get("data", []))
    while "nextUri" in payload:
        r2 = urllib.request.urlopen(payload["nextUri"], timeout=timeout)
        payload = json.loads(r2.read())
        rows.extend(payload.get("data", []))
    return payload, rows


@pytest.fixture(scope="module")
def fo(tmp_path_factory):
    from trino_tpu.fleet import FleetServer
    d = tmp_path_factory.mktemp("failover")
    rg_path = str(d / "rg.json")
    with open(rg_path, "w") as fh:
        json.dump(FAILOVER_RG, fh)
    server = FleetServer(
        workers=2, resource_groups_path=rg_path,
        engine_env={"TRINO_TPU_LAKE_DIR": str(d / "lake")},
        probe_interval_s=0.2, probe_timeout_s=1.0,
        breaker_reset_s=0.5, forward_backoff_s=0.02,
        drain_timeout_s=6.0,
        warmup_manifest={"statements": [
            {"name": "fo_probe",
             "sql": "SELECT n_name, n_regionkey FROM nation "
                    "WHERE n_nationkey = ?",
             "using": "0"}]}).start()
    yield server
    server.stop()


def _wait_engine_state(fo, epoch, state="active", timeout_s=90.0):
    from trino_tpu.fleet.registry import read_engine_record
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        rec = read_engine_record(fo.fleet_dir)
        if rec and int(rec.get("epoch", -1)) >= epoch \
                and rec.get("state") == state:
            return rec
        time.sleep(0.1)
    raise TimeoutError(f"engine epoch {epoch} not {state}")


def _prime_hit(fo, sql):
    """Run `sql` until a WORKER answers it from the shared tier."""
    payload, rows = _http(fo.base_uri, sql)
    assert payload["stats"]["state"] == "FINISHED"
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        payload, got = _http(fo.base_uri, sql)
        if "_fleet_" in payload.get("id", ""):     # worker-served hit
            return got
        time.sleep(0.1)
    # fall back on result equality: the hit path is asserted below by
    # serving through a DEAD engine, which only the tier can do
    return rows


def test_engine_crash_failover(fo):
    """kill -9 the engine mid-fleet: hits keep serving from shm with
    zero errors, a miss answers the classified retryable
    ENGINE_UNAVAILABLE (not a connection reset), the supervisor
    respawns a rehydrated generation, and headerless EXECUTE resolves
    against it (prepared registry rehydration)."""
    from trino_tpu.fleet.supervisor import read_supervisor_record
    hit_sql = "EXECUTE fo_probe USING 5"
    before_rows = _prime_hit(fo, hit_sql)
    assert before_rows == [["ETHIOPIA", 0]]
    old_pid = fo.engine_proc.pid
    epoch_before = fo.engine_epoch
    os.kill(old_pid, signal.SIGKILL)

    # degraded mode: shared-tier hits never notice the dead engine
    outage_hits = 0
    saw_unavailable = False
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not saw_unavailable:
        payload, rows = _http(fo.base_uri, hit_sql)
        assert payload["stats"]["state"] == "FINISHED", payload
        assert rows == before_rows        # zero stale reads, ever
        outage_hits += 1
        # a MISS during the outage: classified, retryable, named
        p2, _ = _http(fo.base_uri, "SELECT count(*) + 17 FROM nation",
                      timeout=60)
        err = p2.get("error")
        if err is None:
            # the supervisor already won the race; that's the next
            # assertion's job
            break
        assert err["errorName"] == "ENGINE_UNAVAILABLE", err
        assert err["errorType"] == "INTERNAL_ERROR"
        saw_unavailable = True
    assert outage_hits >= 1
    # the taxonomy the client replays on: classified AND retryable
    from trino_tpu.errors import ENGINE_UNAVAILABLE
    assert ENGINE_UNAVAILABLE.retryable
    assert ENGINE_UNAVAILABLE.code == 65544

    # supervised recovery: a NEW pid, epoch bumped, crash counted
    rec = _wait_engine_state(fo, epoch=epoch_before + 1)
    assert int(rec["pid"]) != old_pid
    # crash is counted at restart START, outage accumulated at the END
    # of the respawn — wait for both writes, not just the first
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        sup = read_supervisor_record(fo.fleet_dir) or {}
        if ((sup.get("engine_restarts") or {}).get("crash", 0) >= 1
                and sup.get("outage_seconds", 0) > 0):
            break
        time.sleep(0.2)
    sup = read_supervisor_record(fo.fleet_dir)
    assert sup["engine_restarts"]["crash"] >= 1
    assert sup["outage_seconds"] > 0

    # misses resolve again (breaker reset via the engine_epoch notice)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        p3, rows3 = _http(fo.base_uri,
                          "SELECT count(*) + 17 FROM nation", timeout=60)
        if p3["stats"]["state"] == "FINISHED":
            assert rows3 == [[42]]
            break
        time.sleep(0.3)
    else:
        raise AssertionError("miss never recovered after engine respawn")

    # prepared rehydration: a HEADERLESS EXECUTE of the warmed name,
    # with a parameter value nobody cached, must execute on the NEW
    # generation (the registry snapshot rehydrated its prepared map)
    p4, rows4 = _http(fo.base_uri, "EXECUTE fo_probe USING 11",
                      timeout=60)
    assert p4["stats"]["state"] == "FINISHED", p4
    assert rows4 == [["IRAQ", 4]]
    # and the pre-crash hit still serves, still correct
    _, rows5 = _http(fo.base_uri, hit_sql)
    assert rows5 == before_rows


def test_insert_replay_exactly_once_across_crash(fo):
    """The idempotent-write token makes a client replay of an INSERT
    exactly-once even when the engine DIED after committing: the lake
    manifest's committed-token ledger survives the process."""
    _http(fo.base_uri,
          "CREATE TABLE lake.default.fo_once (a BIGINT)", timeout=60)
    tok_hdr = {"X-Trino-Session": "write_token=fo-tok-1"}
    p, _ = _http(fo.base_uri,
                 "INSERT INTO lake.default.fo_once VALUES (1)",
                 headers=tok_hdr, timeout=60)
    assert p["stats"]["state"] == "FINISHED", p
    old_pid = fo.engine_proc.pid
    epoch_before = fo.engine_epoch
    os.kill(old_pid, signal.SIGKILL)
    _wait_engine_state(fo, epoch=epoch_before + 1)
    # the replay: same statement, same token, NEW engine generation
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        p2, _ = _http(fo.base_uri,
                      "INSERT INTO lake.default.fo_once VALUES (1)",
                      headers=tok_hdr, timeout=60)
        if p2["stats"]["state"] == "FINISHED":
            break
        time.sleep(0.3)
    else:
        raise AssertionError("replay INSERT never succeeded")
    _, rows = _http(fo.base_uri,
                    "SELECT count(*) FROM lake.default.fo_once",
                    headers={"X-Trino-Session":
                             "result_cache_enabled=false"}, timeout=60)
    assert rows == [[1]]       # the replay deduped: exactly once
    # a DIFFERENT token appends normally
    p3, _ = _http(fo.base_uri,
                  "INSERT INTO lake.default.fo_once VALUES (2)",
                  headers={"X-Trino-Session": "write_token=fo-tok-2"},
                  timeout=60)
    assert p3["stats"]["state"] == "FINISHED"
    _, rows = _http(fo.base_uri,
                    "SELECT count(*) FROM lake.default.fo_once",
                    headers={"X-Trino-Session":
                             "result_cache_enabled=false"}, timeout=60)
    assert rows == [[2]]


def test_worker_respawn_after_kill(fo):
    """Satellite: a worker dying mid-flight is respawned by the
    supervisor; the fleet returns to full strength with a new pid."""
    before = {r["pid"] for r in fo.workers()}
    assert len(before) == 2
    victim_pid = sorted(before)[0]
    os.kill(victim_pid, signal.SIGKILL)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        recs = fo.workers()
        pids = {r["pid"] for r in recs}
        if len(recs) == 2 and victim_pid not in pids:
            break
        time.sleep(0.2)
    else:
        raise AssertionError(f"worker fleet never recovered: "
                             f"{fo.workers()}")
    # the replacement serves: a hit through the shared port still lands
    payload, _ = _http(fo.base_uri, "EXECUTE fo_probe USING 5")
    assert payload["stats"]["state"] == "FINISHED"


def test_planned_engine_restart_zero_drop_misses(fo):
    """THE acceptance bar: engine_restart() under a closed loop of
    cache MISSES completes with zero errors — the replacement warms up
    first, the old generation drains, and the listener crosses over
    SCM_RIGHTS so no connection ever lands on a dead port."""
    from trino_tpu.fleet.bench_client import run as client_run
    _http(fo.base_uri, "EXECUTE fo_probe USING 3")
    epoch_before = fo.engine_epoch
    result = {}

    def _swap():
        time.sleep(1.0)
        result["epoch"] = fo.engine_restart()

    th = threading.Thread(target=_swap, daemon=True)
    th.start()
    rec = client_run("127.0.0.1", fo.port, duration_s=25.0,
                     warmup_s=0.0, threads=3, mode="miss",
                     probe="fo_probe", values=25)
    th.join(timeout=120)
    assert result.get("epoch") == epoch_before + 1
    assert rec["errors"] == 0, rec
    assert rec["completed"] > 50, rec
    # post-swap sanity: the new generation executes and serves hits
    payload, rows = _http(fo.base_uri, "EXECUTE fo_probe USING 21",
                          timeout=60)
    assert payload["stats"]["state"] == "FINISHED"
    assert rows == [["VIETNAM", 2]]


def test_failover_metrics_surface(fo):
    """The observability satellite wiring: supervisor counters, breaker
    state, deferred-miss counters, and bus drop counts all land in ONE
    shared-port scrape."""
    text = urllib.request.urlopen(f"{fo.base_uri}/v1/metrics",
                                  timeout=30).read().decode()
    assert 'trino_tpu_engine_restarts_total{kind="crash"}' in text
    assert "trino_tpu_engine_outage_seconds" in text
    assert "trino_tpu_fleet_breaker_state" in text
    assert "trino_tpu_fleet_worker_deferred_misses" in text
    assert "trino_tpu_engine_epoch" in text
    # the crash tests above dropped hit batches on a dead engine socket
    assert "trino_tpu_fleet_bus_drops_total" in text
    # counts match the supervisor's own record
    from trino_tpu.fleet.supervisor import read_supervisor_record
    sup = read_supervisor_record(fo.fleet_dir)
    assert sup["engine_restarts"]["planned"] >= 1
    assert sup["engine_restarts"]["crash"] >= 2


def test_zz_poison_statement_stops_crash_loop(fo):
    """Poison-statement quarantine end to end: a digest stamped in
    flight across two crash-correlated engine restarts is published to
    poison.json, the supervisor record tells the story, and the workers
    then fast-fail the statement with the non-retryable
    STATEMENT_QUARANTINED taxonomy instead of crash-looping the
    replacement engine. Innocent statements keep executing."""
    from trino_tpu.fleet import supervisor as sup
    sql = "SELECT 41999 + 1"
    digest = sup.statement_digest(sql)
    for qid in ("q-poison-1", "q-poison-2"):
        # the record going active races the supervisor swapping in the
        # new Popen handle — wait for a LIVE engine process to murder
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline \
                and fo.engine_proc.poll() is not None:
            time.sleep(0.05)
        assert fo.engine_proc.poll() is None
        epoch = fo.engine_epoch
        # stamp the statement in flight exactly as the engine-side
        # observer does, then die before clearing it
        sup.StatementStamper(fo.fleet_dir, epoch=epoch).begin(sql, qid)
        os.kill(fo.engine_proc.pid, signal.SIGKILL)
        _wait_engine_state(fo, epoch=epoch + 1)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline \
            and digest not in sup.read_poison(fo.fleet_dir):
        time.sleep(0.1)
    rec = sup.read_poison(fo.fleet_dir)[digest]
    assert rec["crashes"] >= 2 and rec["sql"] == sql
    assert rec["query_id"] == "q-poison-2"
    sup_rec = sup.read_supervisor_record(fo.fleet_dir)
    assert digest in sup_rec["poisoned"]
    # every worker fast-fails it now — the engine never sees it
    for _ in range(3):
        payload, _rows = _http(fo.base_uri, sql)
        assert payload["stats"]["state"] == "FAILED"
        assert payload["error"]["errorName"] == "STATEMENT_QUARANTINED"
        assert payload["error"]["errorType"] == "INTERNAL_ERROR"
    # an innocent statement still executes through the same fleet
    payload2, rows2 = _http(fo.base_uri, "SELECT 2 + 2")
    assert payload2["stats"]["state"] == "FINISHED"
    assert rows2 == [[4]]
    # the gauge surfaces on the fleet scrape
    text = urllib.request.urlopen(
        f"{fo.base_uri}/v1/metrics", timeout=10).read().decode()
    assert "trino_tpu_fleet_poisoned_statements" in text
