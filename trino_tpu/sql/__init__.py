"""SQL frontend: lexer, parser, AST, analyzer.

Reference parity: core/trino-parser (grammar SqlBase.g4, AstBuilder, 224 AST
nodes in sql/tree/) + core/trino-main sql/analyzer/. The reference uses an
ANTLR4-generated parser; here a hand-written recursive-descent parser keeps the
frontend dependency-free (SURVEY.md §2.2 "TPU build" column).
"""

from trino_tpu.sql.parser import parse_statement, parse_expression  # noqa: F401
