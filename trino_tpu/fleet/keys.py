"""Worker-side statement keying: the engine's result-cache key, without
the engine.

A fleet worker answers result-cache hits locally, so it must compute —
from nothing but the SQL text, the request headers, and the fleet's
prepared-statement registry — the EXACT key the engine's runner used
when it published the result (exec/runner._result_cache_key): the
plan-cache key (canonical literal-free statement fingerprint + masked
literal values + catalog/schema/current_date + bound parameter types +
plan-affecting session properties) plus the bound parameter values.
Both sides then collapse the key to a 16-byte digest
(fleet/shm.key_fingerprint), which is what the shared tier is keyed on.

Parsing and fingerprinting are pure functions of the statement text, so
no catalog resolution (and no device, no planner) is needed — and the
result is memoized per (sql, context) so the steady-state hit path is a
dict lookup, not a parse.
"""

from __future__ import annotations

import collections
import re
import threading
from typing import Any, Dict, Optional, Tuple

from trino_tpu import types as T
from trino_tpu.exec.plan_cache import PLAN_PROPERTIES, statement_fingerprint
from trino_tpu.fleet.shm import key_fingerprint
from trino_tpu.metadata import SESSION_PROPERTY_DEFAULTS, _coerce_property

MEMO_MAX = 8192

# request gates mirroring the server's POST-time probe: only these
# statement heads can resolve to a cached result
PROBE_HEADS = ("SELECT", "EXECUTE", "WITH", "VALUES", "(", "TABLE")


class KeyInfo:
    __slots__ = ("digest", "cacheable")

    def __init__(self, digest: Optional[bytes]):
        self.digest = digest
        self.cacheable = digest is not None


class StatementKeyer:
    def __init__(self, catalog: Optional[str], schema: Optional[str],
                 start_date: int,
                 base_properties: Optional[Dict[str, Any]] = None):
        self.catalog = catalog
        self.schema = schema
        self.start_date = start_date
        # the engine base session's plan-affecting property values: a
        # worker must key exactly like the engine's session would
        self.base_properties = dict(base_properties or {})
        self._lock = threading.Lock()
        self._memo: "collections.OrderedDict[tuple, KeyInfo]" = \
            collections.OrderedDict()

    # ------------------------------------------------------------- context

    def _plan_props(self, overrides: Dict[str, str]) -> Tuple:
        out = []
        for prop in PLAN_PROPERTIES:
            if prop in overrides:
                value = _coerce_property(prop, overrides[prop])
            elif prop in self.base_properties:
                value = self.base_properties[prop]
            else:
                value = SESSION_PROPERTY_DEFAULTS[prop]
            out.append((prop, value))
        return tuple(out)

    # -------------------------------------------------------------- keying

    def key_for(self, sql: str, overrides: Dict[str, str],
                catalog: Optional[str], schema: Optional[str],
                prepared: Dict[str, str]) -> Optional[bytes]:
        """16-byte shared-tier digest for `sql` under the request's
        session context, or None when the statement cannot be keyed
        without the engine (non-query, NULL parameters, unknown prepared
        name, parse trouble — all of which defer to the dispatch path).
        `prepared` maps parser-normalized names to statement SQL (fleet
        registry merged with the request's own header)."""
        head = sql.lstrip()[:8].upper()
        if not head.startswith(PROBE_HEADS):
            return None
        catalog = catalog or self.catalog
        schema = schema or self.schema
        plan_props = self._plan_props(overrides)
        prepared_sig = None
        if head.startswith("EXECUTE"):
            # the memo must key on the prepared statement's TEXT, not
            # its name — DEALLOCATE + re-PREPARE under one name must
            # not serve the old statement's key
            name = self._execute_name(sql)
            if name is None:
                return None
            prepared_sig = prepared.get(name)
            if prepared_sig is None:
                return None
        memo_key = (sql, catalog, schema, plan_props, prepared_sig)
        with self._lock:
            info = self._memo.get(memo_key)
            if info is not None:
                self._memo.move_to_end(memo_key)
                return info.digest
        info = KeyInfo(self._compute(sql, catalog, schema, plan_props,
                                     prepared))
        with self._lock:
            self._memo[memo_key] = info
            while len(self._memo) > MEMO_MAX:
                self._memo.popitem(last=False)
        return info.digest

    _EXEC_NAME = re.compile(
        r'^\s*execute\s+("(?:[^"]|"")*"|[A-Za-z_][A-Za-z0-9_]*)\b',
        re.IGNORECASE)

    @classmethod
    def _execute_name(cls, sql: str) -> Optional[str]:
        """Parser-normalized EXECUTE statement name. Regex fast path —
        this runs BEFORE the memo on every EXECUTE, so a full parse
        here would cost as much as the computation the memo avoids
        (unquoted identifiers lowercase, quoted verbatim with ""
        unescaped — the parser's normalization). Falls back to the
        parser for anything the regex doesn't recognize."""
        m = cls._EXEC_NAME.match(sql)
        if m is not None:
            name = m.group(1)
            if name.startswith('"'):
                return name[1:-1].replace('""', '"')
            return name.lower()
        from trino_tpu.sql import parse_statement
        from trino_tpu.sql import tree as t
        try:
            stmt = parse_statement(sql)
        except Exception:
            return None
        if not isinstance(stmt, t.ExecuteStatement):
            return None
        return stmt.name.value

    def _compute(self, sql: str, catalog, schema, plan_props,
                 prepared: Dict[str, str]) -> Optional[bytes]:
        from trino_tpu.sql import parse_statement
        from trino_tpu.sql import tree as t
        from trino_tpu.sql.analyzer import count_parameters
        try:
            stmt = parse_statement(sql)
        except Exception:
            return None
        params: Tuple[Any, ...] = ()
        param_types = None
        if isinstance(stmt, t.ExecuteStatement):
            text = prepared.get(stmt.name.value)
            if text is None:
                return None
            try:
                target = parse_statement(text)
            except Exception:
                return None
            if not isinstance(target, t.Query):
                return None
            if count_parameters(target) != len(stmt.parameters):
                return None
            if stmt.parameters:
                bound = self._bind_parameters(stmt)
                if bound is None:
                    return None
                param_types, params = bound
                if any(v is None for v in params):
                    return None    # NULLs re-plan engine-side
            stmt = target
        if not isinstance(stmt, t.Query):
            return None
        skeleton, values = statement_fingerprint(stmt)
        plan_key = (skeleton, values, catalog, schema, self.start_date,
                    None if param_types is None
                    else tuple(t_.display() for t_ in param_types),
                    plan_props)
        return key_fingerprint((plan_key, params))

    def _bind_parameters(self, stmt):
        """USING values -> (types, python values); the runner's
        _bind_execute_parameters contract (constants only, negation
        folded, strings normalize to unbounded varchar)."""
        from trino_tpu.expr.ir import Call as IRCall, Literal as IRLiteral
        from trino_tpu.metadata import Session
        from trino_tpu.planner.translate import ExpressionTranslator, Scope
        session = Session(catalog=self.catalog, schema=self.schema,
                          start_date=self.start_date)
        tr = ExpressionTranslator(Scope([]), session=session)
        types, values = [], []
        for expr in stmt.parameters:
            try:
                lit = tr.translate(expr)
            except Exception:
                return None
            if isinstance(lit, IRCall) and lit.name == "negate" and \
                    isinstance(lit.args[0], IRLiteral):
                lit = IRLiteral(-lit.args[0].value, lit.type)
            if not isinstance(lit, IRLiteral):
                return None
            typ = lit.type
            if T.is_string(typ):
                typ = T.VARCHAR
            types.append(typ)
            values.append(lit.value)
        return tuple(types), tuple(values)
