"""Import hygiene: every trino_tpu module imports cleanly in isolation.

The observability layer threads through runner, planner, tracker, server,
and connectors — exactly the shape that breeds circular imports that only
bite when a module is imported FIRST (e.g. a tool importing
trino_tpu.obs.metrics before trino_tpu.exec). Simulate first-import for
each module by stripping every trino_tpu entry from sys.modules and
importing just that module; the original module objects are restored
afterwards so identity-sensitive state (TRACKER, NODE_POOL, jit cache)
is untouched for the rest of the suite.
"""

import importlib
import pathlib
import sys

import pytest

import trino_tpu

_ROOT = pathlib.Path(trino_tpu.__file__).parent


def _all_modules():
    mods = ["trino_tpu"]
    for path in sorted(_ROOT.rglob("*.py")):
        rel = path.relative_to(_ROOT)
        parts = list(rel.parts[:-1])
        stem = rel.stem
        if stem != "__init__":
            parts.append(stem)
        if parts:
            mods.append("trino_tpu." + ".".join(parts))
    return mods


MODULES = _all_modules()


def test_module_inventory_sane():
    assert "trino_tpu.obs.metrics" in MODULES
    assert "trino_tpu.exec.runner" in MODULES
    assert len(MODULES) > 30


@pytest.mark.parametrize("module", MODULES)
def test_module_imports_in_isolation(module):
    saved = {name: mod for name, mod in sys.modules.items()
             if name == "trino_tpu" or name.startswith("trino_tpu.")}
    for name in list(saved):
        del sys.modules[name]
    try:
        importlib.import_module(module)
    finally:
        # drop the freshly-created duplicates, restore the originals
        for name in list(sys.modules):
            if name == "trino_tpu" or name.startswith("trino_tpu."):
                del sys.modules[name]
        sys.modules.update(saved)
