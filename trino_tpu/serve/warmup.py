"""Warmup/preload manifest: a cold server start that serves warm.

Reference parity: production deployments front the reference with
warm-up query storms (benchto's prewarm phase) because the first run of
every shape pays planning + codegen. On this engine the costs are plan
cache misses and XLA compiles — both cacheable — so the server takes a
MANIFEST of representative statements at startup
(`TrinoServer(warmup_manifest=...)` or $TRINO_TPU_WARMUP_MANIFEST),
PREPAREs the named ones into the shared prepared-statement map, and
executes each once: that populates the plan cache (value-free keys for
prepared statements — ANY later parameter values hit), traces every
kernel of the shape into the jit cache (loading compiled binaries from
the persistent compilation cache when one is configured, so even the
XLA compile is a disk read), and optionally seeds the result cache.
The first real user request then binds + dispatches: plan_cache_hits=1,
jit_misses=0.

Manifest format (JSON; a bare list of statement specs also loads):

    {"statements": [
      {"name": "dash_q6", "sql": "SELECT ... WHERE l_quantity < ?",
       "using": "24"},
      {"sql": "SELECT count(*) FROM nation"}
    ]}

`name` + `sql` with `?` markers -> PREPARE name FROM sql, then (when
`using` is present) EXECUTE name USING <using>. Plain `sql` executes
directly. A failing statement is recorded in the report and does NOT
abort the server start — a partially warm server beats no server.

The manifest also learns `tables:` entries — DATA warmup, not just
plans: each named table's columns are read through its connector ONCE
at start() and promoted straight into the device table cache
(exec/table_cache.py), so the FIRST real scan is an HBM hit with zero
host->device staging:

    {"tables": [
      {"table": "lake.default.orders_part"},
      {"table": "tpch.tiny.nation", "columns": ["n_nationkey",
                                                "n_name"]}
     ],
     "statements": [...]}

`table` is catalog.schema.table (or schema.table / table, resolved
against the runner's session); `columns` defaults to every column.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional, Union


def load_manifest(source: Union[str, dict, list]) -> List[Dict[str, Any]]:
    """Path / parsed dict / bare list -> the statement-spec list."""
    if isinstance(source, str):
        with open(source) as f:
            source = json.load(f)
    if isinstance(source, list):
        statements = source
    elif isinstance(source, dict):
        statements = source.get("statements")
        if statements is None and "tables" in source:
            statements = []     # a data-only manifest is legitimate
        if statements is None:
            raise ValueError(
                "warmup manifest needs a top-level 'statements' list "
                f"(got keys: {sorted(source)})")
    else:
        raise ValueError(
            f"warmup manifest must be a path, dict, or list, "
            f"not {type(source).__name__}")
    out = []
    for i, spec in enumerate(statements):
        if not isinstance(spec, dict) or "sql" not in spec:
            raise ValueError(
                f"warmup statement #{i} needs an object with 'sql' "
                f"(got {spec!r})")
        unknown = sorted(set(spec) - {"name", "sql", "using"})
        if unknown:
            # same strictness as resource-group config: a typo'd key must
            # not silently skip the warmup the operator asked for
            raise ValueError(
                f"warmup statement #{i}: unknown keys {unknown}")
        out.append(spec)
    return out


def load_tables(source: Union[str, dict, list]) -> List[Dict[str, Any]]:
    """The manifest's `tables:` preload specs (empty for bare lists)."""
    if isinstance(source, str):
        with open(source) as f:
            source = json.load(f)
    if not isinstance(source, dict):
        return []
    tables = source.get("tables") or []
    out = []
    for i, spec in enumerate(tables):
        if not isinstance(spec, dict) or "table" not in spec:
            raise ValueError(
                f"warmup table #{i} needs an object with 'table' "
                f"(got {spec!r})")
        unknown = sorted(set(spec) - {"table", "columns"})
        if unknown:
            raise ValueError(f"warmup table #{i}: unknown keys {unknown}")
        out.append(spec)
    return out


def preload_table(runner, table: str,
                  columns: Optional[List[str]] = None) -> Dict[str, Any]:
    """Read one table through its connector and promote the columns
    into the runner's device table cache — the first real scan is then
    an HBM hit with zero host->device staging."""
    import jax

    if not bool(runner.session.get("table_cache_enabled")):
        # promoting into a tier no query will ever consult would pin
        # HBM (pool cache reservation) for nothing
        raise ValueError(
            "table_cache_enabled is false on this server — `tables:` "
            "warmup entries need the device table cache on")
    qname = runner.metadata.resolve_table_name(
        tuple(table.split(".")), runner.session)
    conn = runner.catalogs.get(qname.catalog)
    handle = conn.metadata.get_table_handle(qname.schema_table)
    if handle is None:
        raise ValueError(f"table not found: {table}")
    all_handles = conn.metadata.get_column_handles(handle)
    if columns:
        by_name = {c.name: c for c in all_handles}
        missing = [c for c in columns if c not in by_name]
        if missing:
            raise ValueError(f"{table}: unknown columns {missing}")
        handles = [by_name[c] for c in columns]
    else:
        handles = list(all_handles)
    stats = conn.metadata.get_table_statistics(handle)
    rows = int(stats.row_count or 0)
    cap = 1 << 16
    while cap < rows and cap < (1 << 22):
        cap *= 2
    cache = runner._table_cache
    gen = cache.generation()    # before reading: the promotion guard
    pages = []
    for split in conn.split_manager.get_splits(handle, target_splits=1):
        pages.extend(conn.page_source.pages(split, handles, cap))
    take = getattr(conn, "take_scan_stats", None)
    if take is not None:
        take()      # drop the preload's thread-local scan counters
    counts = [int(c) for c in jax.device_get(
        [p.num_rows for p in pages])] if pages else []
    tkey = (qname.catalog, qname.schema, qname.table)
    cache.configure(int(runner.session.get("table_cache_max_bytes")),
                    int(runner.session.get("table_cache_min_scans")))
    cache.note_scan(tkey, [c.name for c in handles])
    resident = cache.promote_from_pages(
        tkey, [(c.name, c) for c in handles], pages, counts, gen=gen)
    return {"table": str(qname), "columns": len(handles),
            "rows": int(sum(counts)), "resident": bool(resident)}


def apply_warmup(runner, source: Union[str, dict, list]
                 ) -> List[Dict[str, Any]]:
    """Run the manifest against `runner` (the server's BASE runner, so
    PREPAREd names land in the shared map every request can EXECUTE).
    Preloads `tables:` into the device table cache first (data warmup),
    then PREPAREs/executes the statements (plan + kernel warmup).
    Returns the per-entry report: what warmed, what it cost, what the
    first real request will now skip."""
    report: List[Dict[str, Any]] = []
    for spec in load_tables(source):
        entry: Dict[str, Any] = {"table": spec["table"]}
        t0 = time.perf_counter()
        try:
            entry.update(preload_table(runner, spec["table"],
                                       spec.get("columns")))
            entry["wall_s"] = round(time.perf_counter() - t0, 4)
        except Exception as e:  # noqa: BLE001 — warm what we can
            entry["error"] = f"{type(e).__name__}: {str(e)[:160]}"
        report.append(entry)
    for spec in load_manifest(source):
        name = spec.get("name")
        label = name or spec["sql"][:60]
        entry: Dict[str, Any] = {"statement": label}
        t0 = time.perf_counter()
        try:
            if name:
                runner.execute(f"PREPARE {name} FROM {spec['sql']}")
                if spec.get("using"):
                    runner.execute(
                        f"EXECUTE {name} USING {spec['using']}")
            else:
                runner.execute(spec["sql"])
            stats = runner.last_query_stats
            entry.update({
                "wall_s": round(time.perf_counter() - t0, 4),
                "jit_misses": int(stats.get("jit_misses", 0)),
                "plan_cached": int(stats.get("plan_cache_misses", 0)) > 0
                or int(stats.get("plan_cache_hits", 0)) > 0,
            })
        except Exception as e:  # noqa: BLE001 — warm what we can
            entry["error"] = f"{type(e).__name__}: {str(e)[:160]}"
        report.append(entry)
    return report
