"""Connector SPI + tpch/memory/blackhole connector tests.

Mirrors plugin/trino-tpch/src/test/ TestTpchMetadata and the BaseConnectorTest
capability pattern (SURVEY.md §4).
"""

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.connector import (CatalogManager, ColumnMetadata,
                                 SchemaTableName, TableMetadata)
from trino_tpu.connector import blackhole, memory, tpch
from trino_tpu.page import Column, Page


@pytest.fixture(scope="module")
def tpch_conn():
    return tpch.create_connector()


def test_tpch_metadata(tpch_conn):
    md = tpch_conn.metadata
    assert "tiny" in md.list_schemas()
    tables = md.list_tables("tiny")
    assert SchemaTableName("tiny", "lineitem") in tables
    assert len(tables) == 8

    h = md.get_table_handle(SchemaTableName("tiny", "orders"))
    assert h is not None
    meta = md.get_table_metadata(h)
    names = [c.name for c in meta.columns]
    assert names[:3] == ["o_orderkey", "o_custkey", "o_orderstatus"]
    assert md.get_table_handle(SchemaTableName("tiny", "nope")) is None

    stats = md.get_table_statistics(h)
    assert stats.row_count == 15_000  # tiny = sf0.01


def test_tpch_scan_roundtrip(tpch_conn):
    md = tpch_conn.metadata
    h = md.get_table_handle(SchemaTableName("tiny", "nation"))
    cols = md.get_column_handles(h)
    splits = tpch_conn.split_manager.get_splits(h)
    assert len(splits) == 1
    pages = list(tpch_conn.page_source.pages(splits[0], cols, 64))
    assert len(pages) == 1
    page = pages[0]
    assert int(page.num_rows) == 25
    keys = page.column(0).to_numpy(25)
    assert list(keys) == list(range(25))
    names = page.column(1).to_numpy(25)
    assert "FRANCE" in names and "GERMANY" in names


def test_tpch_lineitem_pages_and_splits(tpch_conn):
    md = tpch_conn.metadata
    h = md.get_table_handle(SchemaTableName("tiny", "lineitem"))
    cols = md.get_column_handles(h)
    splits = tpch_conn.split_manager.get_splits(h, target_splits=4)
    assert len(splits) == 4
    total = 0
    seen_flags = set()
    for s in splits:
        for page in tpch_conn.page_source.pages(s, cols, 8192):
            n = int(page.num_rows)
            assert n <= 8192
            total += n
            flag_col = page.column(8)
            seen_flags.update(flag_col.to_numpy(n))
    assert total == tpch.table_row_count("lineitem", 0.01)
    assert seen_flags == {"R", "A", "N"}


def test_tpch_referential_integrity(tpch_conn):
    li = tpch.get_table("lineitem", 0.01)
    orders = tpch.get_table("orders", 0.01)
    assert set(np.unique(li["l_orderkey"])) <= set(orders["o_orderkey"])
    cust = tpch.get_table("customer", 0.01)
    assert orders["o_custkey"].max() <= cust["c_custkey"].max()
    # dates: ship after order
    odate_by_key = dict(zip(orders["o_orderkey"], orders["o_orderdate"]))
    sample = np.random.default_rng(0).integers(0, len(li["l_orderkey"]), 100)
    for i in sample:
        assert li["l_shipdate"][i] > odate_by_key[li["l_orderkey"][i]]


def test_tpch_pushdown(tpch_conn):
    md = tpch_conn.metadata
    h = md.get_table_handle(SchemaTableName("tiny", "orders"))
    h2 = md.apply_limit(h, 10)
    assert h2.limit == 10
    cols = md.get_column_handles(h2)
    splits = tpch_conn.split_manager.get_splits(h2, target_splits=1)
    pages = list(tpch_conn.page_source.pages(splits[0], cols, 4096))
    assert int(pages[0].num_rows) == 10


def test_memory_connector_write_read():
    conn = memory.create_connector()
    name = SchemaTableName("default", "t1")
    meta = TableMetadata(name, (
        ColumnMetadata("a", T.BIGINT), ColumnMetadata("s", T.VarcharType(10))))
    conn.metadata.create_table(meta)
    h = conn.metadata.get_table_handle(name)

    page = Page((
        Column.from_numpy(np.array([1, 2, 3], dtype=np.int64), T.BIGINT),
        Column.from_numpy(np.array(["x", "y", "x"], dtype=object),
                          T.VarcharType(10)),
    ), 3)
    sink = conn.page_sink(h)
    sink.append_page(page)
    sink.finish()

    cols = conn.metadata.get_column_handles(h)
    splits = conn.split_manager.get_splits(h)
    pages = list(conn.page_source.pages(splits[0], cols, 16))
    out = pages[0]
    assert int(out.num_rows) == 3
    assert list(out.column(0).to_numpy(3)) == [1, 2, 3]
    assert list(out.column(1).to_numpy(3)) == ["x", "y", "x"]

    conn.metadata.drop_table(h)
    assert conn.metadata.get_table_handle(name) is None


def test_memory_connector_nulls():
    conn = memory.create_connector()
    name = SchemaTableName("default", "t2")
    conn.metadata.create_table(TableMetadata(
        name, (ColumnMetadata("a", T.BIGINT),)))
    h = conn.metadata.get_table_handle(name)
    page = Page((
        Column.from_numpy(np.array([7, 0], dtype=np.int64), T.BIGINT,
                          valid=np.array([True, False])),
    ), 2)
    sink = conn.page_sink(h)
    sink.append_page(page)
    sink.finish()   # two-phase sink: staged rows land at commit
    pages = list(conn.page_source.pages(
        h and conn.split_manager.get_splits(h)[0],
        conn.metadata.get_column_handles(h), 8))
    vals = pages[0].column(0).to_numpy(2)
    assert vals[0] == 7 and vals[1] is None


def test_blackhole():
    conn = blackhole.create_connector()
    name = SchemaTableName("default", "sink")
    conn.metadata.create_table(TableMetadata(
        name, (ColumnMetadata("x", T.BIGINT),)))
    h = conn.metadata.get_table_handle(name)
    page = Page((Column.from_numpy(np.arange(5, dtype=np.int64), T.BIGINT),), 5)
    sink = conn.page_sink(h)
    sink.append_page(page)
    sink.finish()   # two-phase sink: the counter lands at commit
    assert conn._metadata.rows_written == 5
    assert list(conn.page_source.pages(
        conn.split_manager.get_splits(h)[0],
        conn.metadata.get_column_handles(h), 8)) == []


def test_catalog_manager():
    cm = CatalogManager()
    cm.register("tpch", tpch.create_connector())
    cm.register("memory", memory.create_connector())
    assert cm.catalogs() == ["memory", "tpch"]
    assert cm.get("tpch").name == "tpch"
    with pytest.raises(KeyError):
        cm.get("nope")


def test_device_gen_matches_host():
    """tpch_dev (jnp) and tpch_gen (numpy) evaluate the SAME stream
    expressions — verify byte-identical output per column over assorted
    row ranges, including lineitem's order-correlated columns."""
    from trino_tpu.connector import tpch_dev, tpch_gen as G
    sf = 0.01
    for table, (cols, _) in tpch.TABLES.items():
        n = tpch.table_row_count(table, sf)
        for start, end in ((0, min(n, 257)), (max(0, n - 100), n)):
            if end <= start:
                continue
            cap = 512
            for name, typ in cols:
                if not tpch_dev.supported(table, name):
                    continue
                dev = np.asarray(
                    tpch_dev.generate(table, sf, name, start, end, cap)
                )[:end - start]
                if G.string_kind(table, name) == "pooled":
                    host = G.codes_chunk(table, sf, name, start, end)
                else:
                    host = G.numeric_chunk(table, sf, name, start, end)
                assert np.array_equal(
                    dev.astype(np.int64), np.asarray(host, np.int64)), \
                    f"{table}.{name} rows [{start},{end}) diverge"
