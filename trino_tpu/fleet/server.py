"""FleetServer: N SO_REUSEPORT worker processes over one device runner.

Reference parity: Trino's production story is a dispatcher fronting many
coordinators; this engine's analog keeps the DEVICE single-owner — one
process holds the runner (jit cache, plan cache, node pool, table
cache) and executes every cache miss — while N worker processes share
the accept load on ONE port and answer result-cache hits from the
cross-process shared tier (fleet/shm.py) without ever touching the
engine. The parent process:

- SUPERVISES the engine: by default the engine is its own subprocess
  (`python -m trino_tpu.fleet.engine`, fleet/engine.py) so a device
  wedge or OOM kills a REPLACEABLE process, not the fleet. The
  supervisor thread (fleet/supervisor.py) detects the death, respawns a
  generation that rehydrates its warm state from the fleet directory,
  and the workers keep serving shared-tier hits the whole time
  (fleet/worker.py degraded mode). `engine_in_process=True` (implied by
  passing a `runner`) keeps the PR-13 topology: the engine runs inside
  this process and crash recovery is out of scope.
- spawns/monitors the worker subprocesses, writes the fleet.json
  rendezvous config (ports, shm path, the engine session's keying
  context), and — in-process mode — ingests the workers' cache-hit
  accounting batches into the engine's resource-group counters and
  query tracker (the subprocess engine ingests its own).
- performs the zero-drop restarts: worker-by-worker rolling restart
  (spawn replacement, drain, wait), and `engine_restart()` — a PLANNED
  engine swap that passes the live dispatch listener to the replacement
  over SCM_RIGHTS (fleet/handoff.py), so even cache MISSES in flight
  during the swap complete with zero errors.
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from trino_tpu.fleet.bus import FleetBus
from trino_tpu.fleet.registry import (PreparedRegistry, ReloadableQuotaMap,
                                      list_worker_records, quota_allows,
                                      read_engine_record, read_fleet_config,
                                      write_fleet_config)
from trino_tpu.fleet.shm import (DEFAULT_DATA_BYTES, SharedCacheTier,
                                 key_fingerprint)
from trino_tpu.fleet.supervisor import FleetSupervisor
from trino_tpu.serve.caches import (DEFAULT_RESULT_MAX_ENTRIES,
                                    ResultSetCache)

WORKER_READY_TIMEOUT_S = 90.0
ENGINE_READY_TIMEOUT_S = 240.0


class MirroredResultSetCache(ResultSetCache):
    """The engine's result cache with the shared tier as a write-through
    mirror. `generation()` snapshots BOTH counters (tier first — the
    wider scope must not be newer than the narrower one), `put` publishes
    to the tier only when the local put survived its own generation
    guard AND the tier's guard accepts the tier-side snapshot, and
    `get` falls back to the tier on a local miss (a restarted engine
    re-adopts the fleet's warm results). Stale publishes stay
    structurally impossible in either direction."""

    def __init__(self, tier: SharedCacheTier,
                 max_entries: int = DEFAULT_RESULT_MAX_ENTRIES):
        super().__init__(max_entries)
        self.tier = tier

    def generation(self):
        tier_gen = self.tier.generation()
        return (tier_gen, super().generation())

    @staticmethod
    def _split(gen):
        return gen if isinstance(gen, tuple) else (None, gen)

    def put(self, key, entry, gen=None) -> bool:
        tier_gen, local_gen = self._split(gen)
        ok = super().put(key, entry, gen=local_gen)
        if ok:
            self.tier.put(key_fingerprint(key), entry, entry.tables,
                          gen=tier_gen)
        return ok

    def get(self, key, count_miss: bool = True):
        entry = super().get(key, count_miss=count_miss)
        if entry is not None:
            return entry
        local_gen = super().generation()    # BEFORE the tier read: an
        # invalidation racing the adoption below must reject it
        found = self.tier.get(key_fingerprint(key))
        if found is None:
            return None
        entry = found[0]
        super().put(key, entry, gen=local_gen)
        return entry

    def invalidate(self, table) -> int:
        n = super().invalidate(table)
        self.tier.invalidate(table)
        return n


class _QuotaGate:
    """The engine's fast-path quota check, rebased onto the fleet-wide
    shared-memory buckets so engine-landed and worker-landed hits drain
    ONE bucket per group. Hot-reloads the quota map on file mtime
    through the same ReloadableQuotaMap the workers use."""

    def __init__(self, shared: SharedCacheTier, rg_path: Optional[str]):
        self.shared = shared
        self.quotas = ReloadableQuotaMap(rg_path)

    def __call__(self, group: str) -> bool:
        return quota_allows(self.shared, self.quotas.current(), group)


class FleetServer:
    def __init__(self, runner=None, workers: int = 2,
                 host: str = "127.0.0.1", port: int = 0,
                 fleet_dir: Optional[str] = None,
                 schema: str = "tiny",
                 resource_groups_path: Optional[str] = None,
                 warmup_manifest=None,
                 in_process: bool = False,
                 engine_in_process: Optional[bool] = None,
                 drain_grace_s: float = 0.5,
                 drain_timeout_s: float = 10.0,
                 shm_data_bytes: int = DEFAULT_DATA_BYTES,
                 worker_env: Optional[Dict[str, str]] = None,
                 engine_env: Optional[Dict[str, str]] = None,
                 probe_interval_s: float = 0.5,
                 probe_timeout_s: float = 2.0,
                 engine_stall_probes: int = 6,
                 worker_respawn_max: int = 3,
                 respawn_backoff_s: float = 0.25,
                 breaker_failure_threshold: int = 3,
                 breaker_reset_s: float = 1.0,
                 forward_retries: int = 3,
                 forward_backoff_s: float = 0.05,
                 handoff_enabled: bool = True,
                 poison_crash_threshold: int = 2,
                 poison_ttl_s: float = 300.0,
                 **engine_kwargs):
        # a caller-supplied runner can only live in THIS process, so it
        # implies the in-process engine; otherwise the engine defaults
        # to a supervised subprocess (in which case engine_kwargs must
        # be JSON-serializable — they ride fleet.json to the child)
        if engine_in_process is None:
            engine_in_process = runner is not None or bool(in_process)
        self.engine_in_process = bool(engine_in_process)
        self.host = host
        self.schema = schema
        self.n_workers = int(workers)
        self.in_process = bool(in_process)
        self.drain_grace_s = float(drain_grace_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.worker_env = dict(worker_env or {})
        self.engine_env = dict(engine_env or {})
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.engine_stall_probes = int(engine_stall_probes)
        self.worker_respawn_max = int(worker_respawn_max)
        self.respawn_backoff_s = float(respawn_backoff_s)
        self.breaker_failure_threshold = int(breaker_failure_threshold)
        self.breaker_reset_s = float(breaker_reset_s)
        self.forward_retries = int(forward_retries)
        self.forward_backoff_s = float(forward_backoff_s)
        self.handoff_enabled = bool(handoff_enabled)
        self.poison_crash_threshold = int(poison_crash_threshold)
        self.poison_ttl_s = float(poison_ttl_s)
        self.warmup_manifest = warmup_manifest
        self.engine_kwargs = engine_kwargs
        self._owns_dir = fleet_dir is None
        self.fleet_dir = fleet_dir or tempfile.mkdtemp(prefix="tpu_fleet_")
        os.makedirs(self.fleet_dir, exist_ok=True)
        self.shm_path = os.path.join(self.fleet_dir, "cache.shm")
        self.shared = SharedCacheTier(self.shm_path, create=True,
                                      data_bytes=int(shm_data_bytes))
        self.resource_groups_path = resource_groups_path
        self.engine = None
        self.runner = None
        self.engine_proc: Optional[subprocess.Popen] = None
        self.engine_epoch = 0
        self.engine_port = 0
        self._engine_expected_down = False
        self._draining: set = set()
        self.supervisor: Optional[FleetSupervisor] = None
        if self.engine_in_process:
            if runner is None:
                from trino_tpu.exec import LocalQueryRunner
                runner = LocalQueryRunner.tpch(schema)
            self.runner = runner
            # the engine: a full single-process TrinoServer on a private
            # loopback port, the sole owner of the device runner
            from trino_tpu.server import TrinoServer
            self.engine = TrinoServer(
                runner, host="127.0.0.1", port=0,
                resource_groups_path=resource_groups_path,
                warmup_manifest=warmup_manifest, **engine_kwargs)
            # swap the engine's result cache for the mirrored one and
            # hang it on the SAME plan-cache invalidation fan-out
            # DDL/INSERT drives — one INSERT drops plans, local caches,
            # the shared tier, and (via the bus notice below) every
            # worker's hot copies
            self._mirrored = MirroredResultSetCache(self.shared)
            runner._result_cache = self._mirrored
            runner._plan_cache.add_invalidation_hook(
                self._mirrored.invalidate)
            runner._plan_cache.add_invalidation_hook(
                self._publish_invalidate)
            self.engine.fast_path_quota = _QuotaGate(self.shared,
                                                     resource_groups_path)
            self.engine_port = self.engine.port
        # in subprocess mode "engine" names the engine CHILD on the bus;
        # the parent is just another member
        bus_name = "engine" if self.engine_in_process else "fleet"
        self.bus = FleetBus(self.fleet_dir, bus_name,
                            on_message=self._on_bus)
        self._procs: Dict[str, subprocess.Popen] = {}
        self._inproc: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self.port = self._pick_port(host, port)
        self.base_uri = f"http://{host}:{self.port}"
        self.fleet_hits_ingested = 0
        if self.engine_in_process:
            self._register_gauges()

    # ----------------------------------------------------------- lifecycle

    @staticmethod
    def _pick_port(host: str, port: int) -> int:
        """Reserve the fleet's shared port: bind with SO_REUSEPORT (so
        the workers' later binds of the same port succeed), read the
        assignment, release. The parent must NOT keep a bound socket —
        a listener that never accepts would eat its share of the
        kernel's SO_REUSEPORT distribution."""
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            if hasattr(socket, "SO_REUSEPORT"):
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            s.bind((host, port))
            return s.getsockname()[1]
        finally:
            s.close()

    @property
    def worker_procs(self) -> Dict[str, subprocess.Popen]:
        return self._procs

    def start(self) -> "FleetServer":
        # sticky prepared statements, leg 0: the warmup manifest's named
        # statements seed the FLEET registry too, so workers can key
        # EXECUTEs of warmed shapes before any client ever PREPAREd one
        # through the fleet — and a respawned engine rehydrates them
        self.prepared = PreparedRegistry(self.fleet_dir)
        if self.warmup_manifest is not None:
            from trino_tpu.serve.warmup import load_manifest
            try:
                for spec in load_manifest(self.warmup_manifest):
                    if spec.get("name") and spec.get("sql"):
                        self.prepared.register(str(spec["name"]).lower(),
                                               spec["sql"])
            except Exception:   # noqa: BLE001 — warmup stays best-effort
                pass
        if self.engine_in_process:
            self.engine.start()
            self._write_config(self._keying_context_local())
        else:
            # the engine port is FIXED for the fleet's lifetime: every
            # respawned generation rebinds (or SCM_RIGHTS-inherits) the
            # same port, so workers never re-resolve their upstream
            self.engine_port = self._pick_port("127.0.0.1", 0)
            self._write_config({})
            self.engine_proc = self._spawn_engine(epoch=1)
            self.engine_epoch = 1
            rec = self._wait_engine(self.engine_proc, "active", 1,
                                    ENGINE_READY_TIMEOUT_S)
            # the engine session's keying context (current_date pin,
            # plan-affecting base properties) is only known once the
            # child built its runner: merge it into fleet.json before
            # any worker reads it
            self._write_config({
                "start_date": rec.get("start_date"),
                "base_properties": rec.get("base_properties") or {},
                "default_group": rec.get("default_group", "global"),
                "catalog": rec.get("catalog", "tpch"),
                "schema": rec.get("schema", self.schema),
            })
        ids = [self.spawn_worker(wait=False)
               for _ in range(self.n_workers)]
        self._wait_ready(ids)
        self.supervisor = FleetSupervisor(
            self, probe_interval_s=self.probe_interval_s,
            probe_timeout_s=self.probe_timeout_s,
            stall_probes=self.engine_stall_probes,
            worker_respawn_max=self.worker_respawn_max,
            respawn_backoff_s=self.respawn_backoff_s,
            poison_crash_threshold=self.poison_crash_threshold,
            poison_ttl_s=self.poison_ttl_s).start()
        return self

    def _keying_context_local(self) -> Dict:
        from trino_tpu.exec.plan_cache import PLAN_PROPERTIES
        session = self.runner.session
        return {
            # the keying context workers must replicate EXACTLY:
            # current_date is pinned at engine-session construction, and
            # any plan-affecting property set on the base session is
            # part of every key
            "start_date": session.start_date,
            "base_properties": {
                p: session.properties[p] for p in PLAN_PROPERTIES
                if p in session.properties},
            "default_group": str(session.get("resource_group")),
            "catalog": session.catalog, "schema": session.schema,
        }

    def _write_config(self, keying_context: Dict) -> None:
        config = {
            "host": self.host, "port": self.port,
            "engine_host": "127.0.0.1", "engine_port": self.engine_port,
            "engine_base": f"http://127.0.0.1:{self.engine_port}",
            "fleet_dir": self.fleet_dir, "shm_path": self.shm_path,
            "schema": self.schema,
            "resource_groups_path": self.resource_groups_path,
            "drain_grace_s": self.drain_grace_s,
            "drain_timeout_s": self.drain_timeout_s,
            "breaker_failure_threshold": self.breaker_failure_threshold,
            "breaker_reset_s": self.breaker_reset_s,
            "forward_retries": self.forward_retries,
            "forward_backoff_s": self.forward_backoff_s,
            "handoff_enabled": self.handoff_enabled,
            "engine_mode": "in-process" if self.engine_in_process
            else "subprocess",
        }
        if not self.engine_in_process:
            config["warmup_manifest"] = self.warmup_manifest
            config["engine_kwargs"] = self.engine_kwargs
        config.update(keying_context)
        write_fleet_config(self.fleet_dir, config)

    # ------------------------------------------------------------ engine

    def _spawn_engine(self, epoch: int,
                      handoff_path: Optional[str] = None
                      ) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "trino_tpu.fleet.engine",
               self.fleet_dir, "--epoch", str(epoch)]
        if handoff_path is not None:
            cmd += ["--handoff", handoff_path]
        else:
            cmd += ["--port", str(self.engine_port)]
        env = dict(os.environ)
        # the engine child owns the device — it inherits the parent's
        # backend selection unmodified; the marker lets the chaos
        # harness's `engine` fault site know a SIGKILL here is fair game
        env["TRINO_TPU_ENGINE_CHILD"] = "1"
        env.update(self.engine_env)
        log_path = os.path.join(self.fleet_dir, "engine.log")
        log = open(log_path, "a")
        proc = subprocess.Popen(cmd, stdout=log,
                                stderr=subprocess.STDOUT, env=env,
                                start_new_session=True)
        log.close()
        return proc

    def _wait_engine(self, proc: subprocess.Popen, state: str,
                     epoch: int, timeout_s: float) -> Dict:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            rec = read_engine_record(self.fleet_dir)
            if rec and int(rec.get("epoch", -1)) == epoch:
                if rec.get("state") == state:
                    return rec
                if rec.get("state") == "failed":
                    raise RuntimeError(
                        f"fleet engine (epoch {epoch}) failed at "
                        f"startup: {rec.get('error')}; see "
                        f"{self.fleet_dir}/engine.log")
            if proc.poll() is not None:
                raise RuntimeError(
                    f"fleet engine (epoch {epoch}) died at startup "
                    f"(rc={proc.returncode}): "
                    f"{self._log_tail('engine.log')}")
            time.sleep(0.05)
        raise TimeoutError(
            f"fleet engine (epoch {epoch}) not {state} within "
            f"{timeout_s}s")

    def _log_tail(self, rel_path: str, nbytes: int = 2000) -> str:
        try:
            with open(os.path.join(self.fleet_dir, rel_path), "rb") as fh:
                fh.seek(0, os.SEEK_END)
                fh.seek(max(0, fh.tell() - nbytes))
                return fh.read().decode("utf-8", "replace").strip()
        except OSError:
            return "<no log>"

    def _respawn_engine(self) -> None:
        """CRASH recovery (called by the supervisor): spawn the next
        generation in bind mode on the SAME engine port. The replacement
        rehydrates prepared statements, warmup priming, and the shared
        tier's warm results before going active (fleet/engine.py), so
        recovery restores the dead generation's steady state."""
        new_epoch = self.engine_epoch + 1
        proc = self._spawn_engine(new_epoch)
        try:
            self._wait_engine(proc, "active", new_epoch,
                              ENGINE_READY_TIMEOUT_S)
        except BaseException:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10.0)
            raise
        self.engine_proc = proc
        self.engine_epoch = new_epoch
        # tell the workers: breakers reset, stale upstream connections
        # drop, the deferred misses' clients can retry NOW
        self.bus.publish({"kind": "engine_epoch", "epoch": new_epoch},
                         exclude_self=True)

    def engine_restart(self, timeout_s: Optional[float] = None) -> int:
        """PLANNED zero-drop engine swap. The replacement generation
        builds its runner and warms up first; the old engine then
        drains fully and passes the live dispatch listener over
        SCM_RIGHTS (fleet/handoff.py) — connections arriving in the
        no-accept gap wait in the kernel backlog, so a closed loop of
        cache MISSES sees zero errors across the swap. With
        `handoff_enabled=False` the swap is stop-then-bind: a brief
        miss outage (covered by the workers' SERVER_SHUTTING_DOWN /
        retry discipline) instead of fd passing. Returns the new
        epoch."""
        if self.engine_in_process:
            raise RuntimeError(
                "engine_restart() needs the subprocess engine "
                "(engine_in_process=False)")
        drain_budget = self.drain_timeout_s + self.drain_grace_s
        timeout_s = timeout_s if timeout_s is not None else \
            ENGINE_READY_TIMEOUT_S + drain_budget
        new_epoch = self.engine_epoch + 1
        old = self.engine_proc
        self._engine_expected_down = True
        try:
            if self.handoff_enabled:
                path = os.path.join(self.fleet_dir,
                                    f"handoff-{new_epoch}.sock")
                proc = self._spawn_engine(new_epoch, handoff_path=path)
                try:
                    self._wait_engine(proc, "ready-for-handoff",
                                      new_epoch, timeout_s)
                    if not self.bus.send_to(
                            "engine", {"kind": "handoff", "path": path}):
                        raise RuntimeError(
                            "old engine unreachable for handoff")
                    old.wait(timeout=drain_budget + 30.0)
                    self._wait_engine(proc, "active", new_epoch,
                                      timeout_s)
                except BaseException:
                    if proc.poll() is None:
                        proc.kill()
                        proc.wait(timeout=10.0)
                    raise
            else:
                self.bus.send_to("engine", {"kind": "stop"})
                try:
                    old.wait(timeout=drain_budget + 30.0)
                except subprocess.TimeoutExpired:
                    old.kill()
                    old.wait(timeout=10.0)
                proc = self._spawn_engine(new_epoch)
                self._wait_engine(proc, "active", new_epoch, timeout_s)
            self.engine_proc = proc
            self.engine_epoch = new_epoch
        finally:
            self._engine_expected_down = False
        if self.supervisor is not None:
            self.supervisor.count_planned_restart()
        self.bus.publish({"kind": "engine_epoch", "epoch": new_epoch},
                         exclude_self=True)
        return new_epoch

    # ----------------------------------------------------------- workers

    def spawn_worker(self, wait: bool = True,
                     timeout_s: float = WORKER_READY_TIMEOUT_S) -> str:
        worker_id = f"w-{uuid.uuid4().hex[:8]}"
        if self.in_process:
            from trino_tpu.fleet.worker import WorkerServer
            server = WorkerServer(read_fleet_config(self.fleet_dir),
                                  worker_id=worker_id).start()
            with self._lock:
                self._inproc[worker_id] = server
        else:
            env = dict(os.environ)
            # workers never execute queries: pin them to the CPU backend
            # so a TPU engine's workers don't fight over the device
            env.setdefault("JAX_PLATFORMS", "cpu")
            env.update(self.worker_env)
            log_path = os.path.join(self.fleet_dir, "workers",
                                    f"{worker_id}.log")
            os.makedirs(os.path.dirname(log_path), exist_ok=True)
            log = open(log_path, "w")
            proc = subprocess.Popen(
                [sys.executable, "-m", "trino_tpu.fleet.worker",
                 self.fleet_dir, worker_id],
                stdout=log, stderr=subprocess.STDOUT, env=env,
                start_new_session=True)
            log.close()
            with self._lock:
                self._procs[worker_id] = proc
        if wait:
            self._wait_ready([worker_id], timeout_s)
        return worker_id

    def _wait_ready(self, worker_ids: List[str],
                    timeout_s: float = WORKER_READY_TIMEOUT_S) -> None:
        """Wait for workers to report active — and RESPAWN, bounded, the
        ones that die on the way up (a lost SO_REUSEPORT bind race, an
        import-time wobble): each logical worker gets
        `worker_respawn_max` extra attempts with exponential backoff
        before startup fails naming the worker, its exit code, and the
        tail of its log."""
        deadline = time.monotonic() + timeout_s
        pending = {wid: wid for wid in worker_ids}   # current -> original
        attempts = {wid: 0 for wid in worker_ids}    # respawns used
        while pending and time.monotonic() < deadline:
            active = {rec.get("worker_id")
                      for rec in list_worker_records(self.fleet_dir)
                      if rec.get("state") == "active"}
            for wid in [w for w in pending if w in active]:
                pending.pop(wid)
            for wid in list(pending):
                with self._lock:
                    proc = self._procs.get(wid)
                if proc is None or proc.poll() is None:
                    continue
                original = pending.pop(wid)
                with self._lock:
                    self._procs.pop(wid, None)
                n = attempts[original] = attempts[original] + 1
                if n > self.worker_respawn_max:
                    raise RuntimeError(
                        f"fleet worker {original} died at startup "
                        f"{n} times (last rc={proc.returncode}); log "
                        f"tail:\n{self._log_tail(f'workers/{wid}.log')}")
                time.sleep(self.respawn_backoff_s * (2 ** (n - 1)))
                replacement = self.spawn_worker(wait=False)
                pending[replacement] = original
            if pending:
                time.sleep(0.05)
        if pending:
            raise TimeoutError(
                f"fleet workers not ready within {timeout_s}s: "
                f"{sorted(pending)}")

    def workers(self) -> List[Dict]:
        return list_worker_records(self.fleet_dir)

    # ------------------------------------------------------ drain/restart

    def drain_worker(self, worker_id: str,
                     timeout_s: Optional[float] = None) -> None:
        # mark BEFORE the drain request: the supervisor must not
        # mistake this planned exit for a crash and respawn it
        self._draining.add(worker_id)
        rec = next((r for r in self.workers()
                    if r.get("worker_id") == worker_id), None)
        if rec is not None:
            import http.client
            try:
                body = json.dumps({"timeout_s": timeout_s}).encode() \
                    if timeout_s is not None else None
                conn = http.client.HTTPConnection(
                    self.host, rec["admin_port"], timeout=5)
                conn.request("POST", "/v1/fleet/drain", body=body)
                conn.getresponse().read()
                conn.close()
                return
            except OSError:
                pass
        self.bus.send_to(worker_id, {"kind": "drain",
                                     "timeout_s": timeout_s})

    def _wait_exit(self, worker_id: str, timeout_s: float) -> bool:
        with self._lock:
            proc = self._procs.pop(worker_id, None)
            inproc = self._inproc.pop(worker_id, None)
        try:
            if inproc is not None:
                return inproc.join(timeout_s)
            if proc is None:
                return True
            try:
                proc.wait(timeout=timeout_s)
                return True
            except subprocess.TimeoutExpired:
                proc.terminate()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5)
                return False
        finally:
            self._draining.discard(worker_id)

    def rolling_restart(self,
                        timeout_s: Optional[float] = None) -> List[str]:
        """Upgrade the fleet worker-by-worker without dropping a query:
        spawn the replacement FIRST (the port briefly has N+1
        listeners), then drain the old worker and wait for its exit.
        Returns the new worker ids."""
        timeout_s = timeout_s if timeout_s is not None else \
            self.drain_timeout_s + self.drain_grace_s + 20.0
        with self._lock:
            old = list(self._procs) + list(self._inproc)
        fresh = []
        for worker_id in old:
            fresh.append(self.spawn_worker(wait=True))
            self.drain_worker(worker_id)
            self._wait_exit(worker_id, timeout_s)
        return fresh

    def stop(self, cleanup: bool = True) -> None:
        # supervision ends FIRST: a shutdown must not look like a crash
        if self.supervisor is not None:
            self.supervisor.stop()
        with self._lock:
            alive = list(self._procs) + list(self._inproc)
        for worker_id in alive:
            self.drain_worker(worker_id, timeout_s=2.0)
        for worker_id in alive:
            self._wait_exit(
                worker_id, self.drain_grace_s + 5.0)
        if self.engine is not None:
            self.engine.stop()
        if self.engine_proc is not None:
            self.bus.send_to("engine", {"kind": "stop"})
            try:
                self.engine_proc.wait(
                    timeout=self.drain_timeout_s + self.drain_grace_s
                    + 15.0)
            except subprocess.TimeoutExpired:
                self.engine_proc.terminate()
                try:
                    self.engine_proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    self.engine_proc.kill()
                    self.engine_proc.wait(timeout=5)
        self.bus.close()
        self.shared.close()
        if cleanup and self._owns_dir:
            shutil.rmtree(self.fleet_dir, ignore_errors=True)

    # ------------------------------------------------------------- the bus

    def _publish_invalidate(self, table) -> None:
        """Plan-cache invalidation hook leg 5 (in-process engine): tell
        every worker to drop its hot local copies NOW. Advisory — the
        shm generation bump the mirrored cache already performed is what
        makes staleness impossible; this just evicts dead weight
        promptly."""
        self.bus.publish({"kind": "invalidate", "table": list(table)},
                         exclude_self=True)

    def _on_bus(self, message: Dict) -> None:
        if self.engine is None:
            return     # subprocess mode: the engine child ingests
        kind = message.get("kind")
        if kind == "hits":
            from trino_tpu.fleet.engine import ingest_hits
            self.fleet_hits_ingested += ingest_hits(self.engine, message)
        elif kind == "prepare":
            # sticky routing leg 2: statements PREPAREd through any
            # worker land in the engine's base prepared map too, so an
            # EXECUTE that reaches the engine without headers resolves
            from trino_tpu.fleet.engine import register_prepared
            register_prepared(self.runner, message["name"],
                              message["sql"])
        elif kind == "deallocate":
            self.runner._prepared.pop(message.get("name"), None)

    # ------------------------------------------------------------- gauges

    def _register_gauges(self) -> None:
        from trino_tpu.obs.metrics import REGISTRY
        fleet = self

        def _fleet_gauges():
            yield ("trino_tpu_fleet_workers",
                   "Live fleet worker processes.",
                   len(fleet.workers()), {})
            yield ("trino_tpu_fleet_shared_cache_entries",
                   "Live entries in the cross-process result cache.",
                   fleet.shared.entry_count(), {})
            yield ("trino_tpu_fleet_hits_ingested",
                   "Worker cache hits ingested into fleet accounting.",
                   fleet.fleet_hits_ingested, {})

        REGISTRY.register_gauges(_fleet_gauges)
