"""Observability: per-operator stats, trace spans, events, metrics.

Reference parity: core/trino-main execution/QueryStats.java +
operator/OperatorStats.java (the per-operator rollup EXPLAIN ANALYZE and
the REST API render), core/trino-spi eventlistener/ (EventListener SPI:
QueryCreatedEvent / QueryCompletedEvent streamed to plugins), and the
JMX/OpenMetrics surface (io.airlift.stats counters exported per MBean)
collapsed to a process-wide registry served at GET /v1/metrics.

This package is the engine's measurement layer: the runner owns one
`QueryStatsCollector` per query, execution threads it through the local
planner, the distributed scheduler, and the jit cache, and everything
downstream — EXPLAIN ANALYZE, system.runtime.{queries,metrics}, event
listeners, Prometheus scrapes, bench.py — reads the same numbers.
"""

from trino_tpu.obs.listeners import (EventListener, LoggingEventListener,
                                     QueryEvent, register_listener,
                                     unregister_listener)
from trino_tpu.obs.metrics import REGISTRY, MetricsRegistry
from trino_tpu.obs.spans import Span
from trino_tpu.obs.stats import OperatorStats, QueryStatsCollector

__all__ = [
    "EventListener", "LoggingEventListener", "QueryEvent",
    "register_listener", "unregister_listener",
    "REGISTRY", "MetricsRegistry", "Span",
    "OperatorStats", "QueryStatsCollector",
]
