"""Parameterized kernel compilation: literal hoisting (expr/hoist.py).

The contract under test (PageFunctionCompiler parity, TPU edition): the
jit-cache key is the literal-free canonical expression tree, so executing
a TPC-H query and then the SAME shape with perturbed numeric/date
constants must (a) produce rows identical to the unhoisted
(hoist_literals=false) execution of the same SQL — the oracle-verified
pre-hoisting code path — and (b) report jit_misses == 0 on the second
run via QueryStatsCollector: zero XLA compiles for a new literal set.

The 22-query sweep doubles as a trace-count regression guard: any change
that sneaks a literal value back into a kernel cache key shows up here as
a nonzero miss count on the variant run.
"""

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.exec import LocalQueryRunner, jit_cache
from trino_tpu.expr.hoist import hoist_literal_seq, hoist_literals
from trino_tpu.expr.ir import Call, InputRef, Literal, Param, SpecialForm, \
    SpecialKind
from trino_tpu.expr.functions import days_from_civil

from oracle import assert_same, load_tpch_sqlite
from tpch_sql import QUERIES

SF = 0.01


def d(text: str) -> int:
    y, m, dd = text.split("-")
    return days_from_civil(int(y), int(m), int(dd))


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch("tiny")


@pytest.fixture(scope="module")
def oracle():
    conn = load_tpch_sqlite(SF)
    yield conn
    conn.close()


# ---------------------------------------------------------------- hoist pass


def test_hoist_numeric_comparison():
    e = Call("lt", (InputRef(0, T.BIGINT), Literal(24, T.BIGINT)),
             T.BOOLEAN)
    canon, values = hoist_literals(e)
    assert canon == Call("lt", (InputRef(0, T.BIGINT),
                                Param(0, T.BIGINT)), T.BOOLEAN)
    assert len(values) == 1
    assert values[0].dtype == np.dtype(np.int64)
    assert values[0].item() == 24
    # different literal, same canonical tree — the whole point
    canon2, values2 = hoist_literals(
        Call("lt", (InputRef(0, T.BIGINT), Literal(25, T.BIGINT)),
             T.BOOLEAN))
    assert canon2 == canon
    assert values2[0].item() == 25


def test_hoist_keeps_strings_nulls_booleans_static():
    vt = T.VARCHAR
    e = SpecialForm(SpecialKind.AND, (
        Call("eq", (InputRef(0, vt), Literal("FOO", vt)), T.BOOLEAN),
        Call("eq", (InputRef(1, T.BIGINT), Literal(None, T.BIGINT)),
             T.BOOLEAN),
        Literal(True, T.BOOLEAN)), T.BOOLEAN)
    canon, values = hoist_literals(e)
    assert canon == e           # nothing hoistable
    assert values == ()


def test_hoist_respects_static_call_annotations():
    vt = T.VARCHAR
    # LIKE pattern + escape stay literal (host like-table)
    like = Call("like", (InputRef(0, vt), Literal("F%", vt)), T.BOOLEAN)
    assert hoist_literals(like)[0] == like
    # substr is fully static, numeric args included (host dict transform)
    sub = Call("substr", (InputRef(0, vt), Literal(1, T.BIGINT),
                          Literal(2, T.BIGINT)), vt)
    assert hoist_literals(sub)[0] == sub
    # date_add: the unit string is static, the count hoists
    da = Call("date_add", (Literal("day", vt), Literal(3, T.BIGINT),
                           InputRef(0, T.DATE)), T.DATE)
    canon, values = hoist_literals(da)
    assert canon.args[0] == Literal("day", vt)
    assert canon.args[1] == Param(0, T.BIGINT)
    assert values[0].item() == 3


def test_hoist_seq_shares_one_numbering():
    es = (Call("add", (InputRef(0, T.BIGINT), Literal(1, T.BIGINT)),
               T.BIGINT),
          Call("multiply", (InputRef(0, T.BIGINT), Literal(2, T.BIGINT)),
               T.BIGINT))
    canon, values = hoist_literal_seq(es)
    assert canon[0].args[1] == Param(0, T.BIGINT)
    assert canon[1].args[1] == Param(1, T.BIGINT)
    assert [v.item() for v in values] == [1, 2]


def test_hoist_decimal_scaled_int_value():
    dt = T.DecimalType(12, 2)
    canon, values = hoist_literals(Literal(605, dt))   # 6.05 scaled
    assert canon == Param(0, dt)
    assert values[0].dtype == np.dtype(dt.dtype)
    assert values[0].item() == 605


# --------------------------------------------------------------- jit cache


def test_param_hit_and_eviction_counters():
    """cached_kernel attribution: same canonical key + new values = a
    param hit; LRU overflow counts evictions. Runs against a scratch
    cache snapshot so the suite's warm kernels survive."""
    with jit_cache._LOCK:
        saved = list(jit_cache._CACHE.items())
        saved_max = jit_cache._MAX_KERNELS
        jit_cache._CACHE.clear()
        jit_cache._MAX_KERNELS = 2
    base = jit_cache.stats()
    try:
        def build():
            return lambda x, p: x
        jit_cache.cached_kernel(("ph-k1",), build, params=(np.int64(1),))
        jit_cache.cached_kernel(("ph-k1",), build, params=(np.int64(1),))
        s = jit_cache.stats()
        assert s["param_hits"] == base["param_hits"]      # same values
        jit_cache.cached_kernel(("ph-k1",), build, params=(np.int64(2),))
        s = jit_cache.stats()
        assert s["param_hits"] == base["param_hits"] + 1  # new values
        # overflow the shrunken LRU: 3rd distinct key evicts the oldest
        jit_cache.cached_kernel(("ph-k2",), build)
        jit_cache.cached_kernel(("ph-k3",), build)
        s = jit_cache.stats()
        assert s["evictions"] >= base["evictions"] + 1
    finally:
        with jit_cache._LOCK:
            jit_cache._MAX_KERNELS = saved_max
            jit_cache._CACHE.clear()
            jit_cache._CACHE.update(saved)


def test_jit_cache_metrics_exported(runner):
    from trino_tpu.obs.metrics import REGISTRY
    runner.execute("SELECT count(*) FROM region")
    text = REGISTRY.render()
    assert "trino_tpu_jit_cache_param_hits" in text
    assert "trino_tpu_jit_cache_evictions_total" in text


def test_compilation_cache_env_var(monkeypatch, tmp_path):
    import jax
    import trino_tpu
    before = jax.config.jax_compilation_cache_dir
    try:
        monkeypatch.setenv("TRINO_TPU_COMPILATION_CACHE_DIR",
                           str(tmp_path))
        trino_tpu.enable_persistent_cache()
        assert jax.config.jax_compilation_cache_dir == str(tmp_path)
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


# ------------------------------------------------- TPC-H literal variants
#
# Engine-SQL rewrites perturbing every hoistable numeric/date constant.
# Static-by-design constants are deliberately NOT touched: LIKE patterns,
# string literals, substring positions, LIMIT/TopN counts, interval UNIT
# strings (the counts inside INTERVAL '<n>' do hoist). Queries absent
# here have no hoistable constants (q9/q13/q21: strings + LIKE only) —
# their "variant" is the identical statement, which must hit outright.

PERTURB = {
    "q1": [("INTERVAL '90' DAY", "INTERVAL '60' DAY")],
    "q2": [("p_size = 15", "p_size = 14")],
    "q3": [("DATE '1995-03-15'", "DATE '1995-03-08'")],
    "q4": [("DATE '1993-07-01'", "DATE '1993-08-01'")],
    "q5": [("DATE '1994-01-01'", "DATE '1995-01-01'")],
    "q6": [("DATE '1994-01-01'", "DATE '1995-01-01'"),
           ("0.06", "0.07"),
           ("l_quantity < 24", "l_quantity < 25")],
    "q7": [("DATE '1995-01-01'", "DATE '1995-02-01'"),
           ("DATE '1996-12-31'", "DATE '1996-11-30'")],
    "q8": [("DATE '1995-01-01'", "DATE '1995-02-01'"),
           ("DATE '1996-12-31'", "DATE '1996-11-30'")],
    "q10": [("DATE '1993-10-01'", "DATE '1993-11-01'")],
    "q11": [("0.0001", "0.0002")],
    "q12": [("DATE '1994-01-01'", "DATE '1995-01-01'")],
    "q14": [("DATE '1995-09-01'", "DATE '1995-04-01'"),
            ("DATE '1995-10-01'", "DATE '1995-05-01'")],
    "q15": [("DATE '1996-01-01'", "DATE '1996-04-01'")],
    "q16": [("(49, 14, 23, 45, 19, 3, 36, 9)",
             "(48, 15, 22, 44, 18, 4, 35, 8)")],
    "q17": [("0.2 * avg", "0.3 * avg")],
    "q18": [("sum(l_quantity) > 200", "sum(l_quantity) > 250")],
    "q19": [("l_quantity >= 1 AND l_quantity <= 11",
             "l_quantity >= 2 AND l_quantity <= 12"),
            ("l_quantity >= 10 AND l_quantity <= 20",
             "l_quantity >= 11 AND l_quantity <= 21"),
            ("l_quantity >= 20 AND l_quantity <= 30",
             "l_quantity >= 21 AND l_quantity <= 31"),
            # upper bound only: `p_size >= 1` is a conjunct COMMON to all
            # three OR branches, which the optimizer extracts into a
            # pushed-down scan filter — perturbing one branch's lower
            # bound breaks the extraction and legitimately changes plan
            # structure (a different shape, not a hoisting gap)
            ("p_size BETWEEN 1 AND 5", "p_size BETWEEN 1 AND 6")],
    "q20": [("0.5 * sum", "0.6 * sum"),
            ("DATE '1994-01-01'", "DATE '1995-01-01'")],
    "q22": [("c_acctbal > 0.00", "c_acctbal > 1.00")],
}


def variant_sql(name: str) -> str:
    sql = QUERIES[name][0]
    for old, new in PERTURB.get(name, []):
        assert old in sql, f"{name}: perturbation target {old!r} not found"
        sql = sql.replace(old, new)
    return sql


@pytest.mark.parametrize("name", list(QUERIES))
def test_literal_variant_zero_jit_misses(runner, name):
    """Acceptance: base literals warm the canonical kernels; the
    perturbed-literal re-run must dispatch ONLY warm executables."""
    engine_sql = QUERIES[name][0]
    runner.execute(engine_sql)
    runner.execute(variant_sql(name))
    stats = runner.last_query_stats
    assert stats["jit_misses"] == 0, (
        f"{name}: literal variant recompiled {stats['jit_misses']} "
        f"kernels (hoisting gap)")
    if PERTURB.get(name):
        assert stats["jit_param_hits"] > 0, (
            f"{name}: perturbed constants never reached a kernel as "
            f"parameters — are they being hoisted at all?")


# parity subset: shapes covering fused filter/project chains, residual
# join filters (q19), HAVING over aggregation (q18/q11), correlated
# scalar subqueries (q17/q20), semi/anti joins (q22)
PARITY = ["q1", "q3", "q6", "q7", "q11", "q12", "q14", "q17", "q18",
          "q19", "q20", "q22"]


@pytest.mark.parametrize("name", PARITY)
def test_hoisted_rows_match_unhoisted(runner, name):
    """The hoisted execution of a perturbed-literal query must be
    row-identical to hoist_literals=false — the literal-embedding
    pre-hoisting code path that test_queries.py oracle-verifies."""
    sql = variant_sql(name)
    ordered = QUERIES[name][2]
    hoisted = runner.execute(sql)
    runner.session.set("hoist_literals", False)
    try:
        unhoisted = runner.execute(sql)
    finally:
        runner.session.properties.pop("hoist_literals", None)
    assert_same(hoisted.rows, unhoisted.rows, ordered)


def test_variant_oracle_parity_q6(runner, oracle):
    got = runner.execute(variant_sql("q6"))
    expected = oracle.execute(f"""
        SELECT sum(l_extendedprice * l_discount) FROM lineitem
        WHERE l_shipdate >= {d('1995-01-01')}
          AND l_shipdate < {d('1996-01-01')}
          AND l_discount BETWEEN 6 AND 8 AND l_quantity < 2500
        """).fetchall()
    assert_same(got.rows, expected, ordered=False)


def test_variant_oracle_parity_q18(runner, oracle):
    got = runner.execute(variant_sql("q18"))
    oracle_sql = QUERIES["q18"][1].replace(
        "sum(l_quantity) > 20000", "sum(l_quantity) > 25000")
    expected = oracle.execute(oracle_sql).fetchall()
    assert_same(got.rows, expected, ordered=True)


def test_round_digits_hoists_trace_safe(runner):
    """round(int_col, d) used Python `if d >= 0` control flow on the
    digits argument, which fails at trace time now that d arrives as a
    traced scalar (pre-existing break the hoisting whitelist audit
    surfaced — it failed under the chain kernel's trace even with the
    constant embedded). The jnp rewrite must round correctly for both
    signs of d and share one kernel across digit values."""
    got = runner.execute(
        "SELECT o_orderkey, round(o_orderkey, -2), round(o_orderkey, 1) "
        "FROM orders ORDER BY o_orderkey LIMIT 50").rows
    for k, rm2, rp1 in got:
        scaled = (abs(k) + 50) // 100 * 100
        assert rm2 == (scaled if k >= 0 else -scaled)
        assert rp1 == k                       # d >= 0: identity on ints
    # same shape, different digits: one kernel (digits are hoisted)
    runner.execute(
        "SELECT round(o_orderkey, -2) FROM orders ORDER BY o_orderkey "
        "LIMIT 50")
    runner.execute(
        "SELECT round(o_orderkey, -3) FROM orders ORDER BY o_orderkey "
        "LIMIT 50")
    assert runner.last_query_stats["jit_misses"] == 0


def test_hoist_literals_off_compiles_per_literal(runner):
    """The debugging pin: with hoisting off, a fresh literal value is a
    fresh cache key — the query pays compiles again."""
    runner.session.set("hoist_literals", False)
    try:
        runner.execute(
            "SELECT count(*) FROM lineitem WHERE l_quantity < 17")
        first = runner.last_query_stats["jit_misses"]
        assert first > 0
        runner.execute(
            "SELECT count(*) FROM lineitem WHERE l_quantity < 18")
        assert runner.last_query_stats["jit_misses"] > 0
    finally:
        runner.session.properties.pop("hoist_literals", None)
    # back on: yet another literal reuses the canonical kernel
    runner.execute("SELECT count(*) FROM lineitem WHERE l_quantity < 16")
    runner.execute("SELECT count(*) FROM lineitem WHERE l_quantity < 19")
    assert runner.last_query_stats["jit_misses"] == 0
