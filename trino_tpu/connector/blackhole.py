"""Blackhole connector: swallow writes, serve empty scans.

Reference parity: plugin/trino-blackhole — benchmarking sink (writes are
counted and dropped).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Sequence

from trino_tpu.connector.spi import (
    ColumnHandle, Connector, ConnectorMetadata, ConnectorPageSink,
    ConnectorPageSource, ConnectorSplitManager, ConnectorTableHandle,
    SchemaTableName, Split, TableMetadata)
from trino_tpu.page import Page


class BlackHoleMetadata(ConnectorMetadata):
    def __init__(self):
        self._tables: Dict[SchemaTableName, TableMetadata] = {}
        self.rows_written = 0
        self._lock = threading.Lock()
        # write tokens already counted: a retried attempt's commit is a
        # no-op, so rows_written stays exact under QUERY-level retry
        # (bounded — see spi.WriteTokenLedger)
        from trino_tpu.connector.spi import WriteTokenLedger
        self._committed_tokens = WriteTokenLedger()

    def list_schemas(self) -> List[str]:
        return ["default"]

    def list_tables(self, schema: Optional[str] = None) -> List[SchemaTableName]:
        return sorted(self._tables, key=lambda n: (n.schema, n.table))

    def get_table_handle(self, name: SchemaTableName):
        return ConnectorTableHandle(name) if name in self._tables else None

    def get_table_metadata(self, handle: ConnectorTableHandle) -> TableMetadata:
        return self._tables[handle.name]

    def create_table(self, metadata: TableMetadata,
                     ignore_existing: bool = False):
        if metadata.name in self._tables and not ignore_existing:
            raise ValueError(f"table already exists: {metadata.name}")
        self._tables[metadata.name] = metadata

    def drop_table(self, handle: ConnectorTableHandle):
        self._tables.pop(handle.name, None)

    def count(self, n: int, token=None):
        with self._lock:
            if token is not None and \
                    not self._committed_tokens.commit(token):
                return
            self.rows_written += n


class BlackHoleSplitManager(ConnectorSplitManager):
    def get_splits(self, handle, target_splits: int = 1) -> List[Split]:
        return [Split(handle, 0, 1)]


class BlackHolePageSource(ConnectorPageSource):
    def pages(self, split: Split, columns: Sequence[ColumnHandle],
              page_capacity: int) -> Iterator[Page]:
        return iter(())


class BlackHolePageSink(ConnectorPageSink):
    """Staged counting sink: rows stage in the sink and hit the global
    counter only at finish(), once per write token (the same
    idempotent-write protocol as the memory connector, with a counter
    where the table would be)."""

    def __init__(self, metadata: BlackHoleMetadata, write_token=None):
        self._metadata = metadata
        self._token = write_token
        self._staged_rows = 0

    def append_page(self, page: Page):
        self._staged_rows += int(page.num_rows)

    def finish(self):
        self._metadata.count(self._staged_rows, token=self._token)
        self._staged_rows = 0

    def abort(self):
        self._staged_rows = 0


class BlackHoleConnector(Connector):
    idempotent_writes = True

    def __init__(self):
        metadata = BlackHoleMetadata()
        super().__init__("blackhole", metadata, BlackHoleSplitManager(),
                         BlackHolePageSource())
        self._metadata = metadata

    def page_sink(self, handle: ConnectorTableHandle,
                  write_token: Optional[str] = None) -> ConnectorPageSink:
        return BlackHolePageSink(self._metadata, write_token)


def create_connector() -> Connector:
    return BlackHoleConnector()
