"""Chaos runs: TPC-H under fault injection, oracle-verified.

Reference parity: testing/trino-faulttolerant-tests
(TestFaultTolerantExecution* — TPC queries stay correct under injected
task failure with RetryPolicy.TASK).

With a FIXED seed the injector's decisions replay exactly, so the green
runs under retry_policy=TASK and the red run under retry_policy=NONE
prove retries (not luck) produced the green results.

Named test_zz_* so these sweeps collect LAST: the tier-1 wall budget
spends on the seed suites first and on chaos afterwards. The full
distributed sweep (all 22 queries, ~12 min) is marked slow; tier-1 keeps
one seed over all 22 queries on the local engine plus a cheap
distributed subset.
"""

import pytest

from trino_tpu.errors import InjectedFault, is_retryable
from trino_tpu.exec import LocalQueryRunner
from trino_tpu.exec.distributed import DistributedQueryRunner

from oracle import assert_same, load_tpch_sqlite
from tpch_sql import PASSING, QUERIES

CHAOS_SEED = 42
CHAOS_RATE = 0.2

# tier-1 distributed chaos subset (cheap fragments); the rest of the
# distributed sweep runs under `slow`
CHEAP_DIST = ["q1", "q6", "q12", "q14"]


def set_chaos(runner, *, seed=CHAOS_SEED, rate=CHAOS_RATE, policy="TASK"):
    runner.session.set("fault_injection_seed", seed)
    runner.session.set("fault_injection_rate", rate)
    runner.session.set("retry_policy", policy)


@pytest.fixture(scope="module")
def oracle():
    conn = load_tpch_sqlite(0.01)
    yield conn
    conn.close()


@pytest.fixture(scope="module")
def chaos_dist():
    runner = DistributedQueryRunner.tpch("tiny")
    set_chaos(runner, policy="TASK")
    return runner


@pytest.fixture(scope="module")
def chaos_local():
    runner = LocalQueryRunner.tpch("tiny")
    set_chaos(runner, policy="TASK")
    return runner


@pytest.mark.parametrize("name", PASSING)
def test_tpch_chaos_local(chaos_local, oracle, name):
    """One seed over ALL 22 queries in tier-1 (local engine: same retry
    scopes — plan task, scan and spill sites — at a fraction of the
    distributed sweep's wall cost)."""
    sql, oracle_sql, ordered = QUERIES[name]
    got = chaos_local.execute(sql)
    expected = oracle.execute(oracle_sql).fetchall()
    assert_same(got.rows, expected, ordered)


@pytest.mark.parametrize("name", CHEAP_DIST)
def test_tpch_chaos_distributed(chaos_dist, oracle, name):
    """Seed 42 / rate 0.2 / retry_policy=TASK — fragment-retry chaos on
    the distributed engine, oracle-verified."""
    sql, oracle_sql, ordered = QUERIES[name]
    got = chaos_dist.execute(sql)
    expected = oracle.execute(oracle_sql).fetchall()
    assert_same(got.rows, expected, ordered)


@pytest.mark.slow
@pytest.mark.parametrize("name", [q for q in PASSING
                                  if q not in CHEAP_DIST])
def test_tpch_chaos_distributed_full(chaos_dist, oracle, name):
    """Acceptance sweep: seed 42 / rate 0.2 / retry_policy=TASK — EVERY
    TPC-H query oracle-verifies despite injected fragment/exchange/scan
    faults (verified green in full before being marked slow for the
    tier-1 wall budget)."""
    sql, oracle_sql, ordered = QUERIES[name]
    got = chaos_dist.execute(sql)
    expected = oracle.execute(oracle_sql).fetchall()
    assert_same(got.rows, expected, ordered)


def test_tpch_chaos_injected_something(chaos_dist, chaos_local):
    """The green sweeps above must actually have seen faults — otherwise
    they prove nothing. Cumulative counters live on the runners."""
    injected = (chaos_local.stats["faults_injected"]
                + chaos_dist.stats["faults_injected"])
    retries = chaos_local.stats["retries"] + chaos_dist.stats["retries"]
    assert injected > 0
    assert retries >= injected


def test_tpch_chaos_retry_none_fails():
    """Same seed, retry_policy=NONE: the sweep fails with a
    retryable-classified error — proof the TASK runs' green came from
    retries, not luck."""
    runner = DistributedQueryRunner.tpch("tiny")
    set_chaos(runner, policy="NONE")
    saw_fault = None
    for name in PASSING:
        sql, _, _ = QUERIES[name]
        try:
            runner.execute(sql)
        except InjectedFault as e:
            saw_fault = e
            break
    assert saw_fault is not None
    assert is_retryable(saw_fault)
    assert saw_fault.error_name == "REMOTE_TASK_ERROR"


@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_tpch_chaos_seed_sweep(oracle, seed):
    """High-iteration chaos: several seeds at a higher rate, local engine
    (cheaper per query, same retry scopes)."""
    runner = LocalQueryRunner.tpch("tiny")
    set_chaos(runner, seed=seed, rate=0.3, policy="TASK")
    for name in PASSING:
        sql, oracle_sql, ordered = QUERIES[name]
        got = runner.execute(sql)
        expected = oracle.execute(oracle_sql).fetchall()
        assert_same(got.rows, expected, ordered)
