"""Incremental materialized views over the lake's versioned manifest
log: mergeable partial-state storage, delta refresh as one SQL merge
INSERT pinned to the manifest diff, query rewrite onto fresh views, and
update-on-write result-cache republish (see manager.py)."""

from trino_tpu.mv.manager import (MaterializedViewManager,      # noqa: F401
                                  all_materialized_view_rows)
