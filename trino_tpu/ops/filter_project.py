"""Fused filter + project operator.

Reference parity: operator/ScanFilterAndProjectOperator.java +
FilterAndProjectOperator.java with their compiled PageProcessor
(operator/project/PageProcessor.java). Here: compile_filter/compile_expression
produce traced jnp, and XLA fuses predicate, compaction, and projections into
one kernel under the fragment's jit.

Parameterized compilation: expressions may carry `Param` leaves
(expr/hoist.py) indexing one shared runtime values tuple for the whole
fused op — hoist the filter and projections together with
hoist_literal_seq so their indices align, then pass that tuple per call.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

from trino_tpu.expr.compiler import compile_expression, compile_filter
from trino_tpu.expr.ir import RowExpression
from trino_tpu.page import Page


def filter_project(
    filter_expr: Optional[RowExpression],
    projections: Sequence[RowExpression],
    params: tuple = (),
) -> Callable[..., Page]:
    """Build op: keep rows passing filter_expr, emit one column per
    projection. `params` is the default hoisted-literal tuple; callers
    running literal variants of the same compiled op pass theirs per
    call: op(page, variant_params)."""
    filter_fn = compile_filter(filter_expr) if filter_expr is not None else None
    project_fns = [compile_expression(p) for p in projections]

    def op(page: Page, call_params: tuple = params) -> Page:
        if filter_fn is not None:
            page = page.filter(filter_fn(page, call_params))
        cols = tuple(fn(page, call_params) for fn in project_fns)
        return Page(cols, page.num_rows)

    return op
