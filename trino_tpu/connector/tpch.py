"""TPC-H generator connector: deterministic in-memory data, no files.

Reference parity: plugin/trino-tpch (TpchMetadata.java, TpchRecordSetProvider
.java, TpchSplitManager.java) — schemas tiny/sf1/sf10/... expose the 8 TPC-H
tables, rows generated on demand. The reference delegates to io.airlift.tpch
(a dbgen port); data here comes from `tpch_gen` — stateless counter-hash
column streams reproducing dbgen's seekability (any column, any row range,
any process, identical bytes) so scans materialize only the columns and row
ranges they touch. That is what makes SF100 runnable on one host: a q9 scan
of 600M-row lineitem generates 7 of 16 columns, chunk by chunk, and pooled
varchar columns are emitted directly as dictionary codes (no Python string
objects on the scan path).

Correctness contract: engine and sqlite oracle read the SAME generated data
(the H2QueryRunner pattern); see tpch_gen's docstring for the documented
re-scope vs dbgen bit-identical rows.

All varchar columns come dictionary-encoded; dates are int32 days since epoch;
prices are short decimals (scaled int64).
"""

from __future__ import annotations

import math
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from trino_tpu import types as T
from trino_tpu.connector import tpch_dev, tpch_gen as G
from trino_tpu.connector.spi import (
    ColumnHandle, ColumnMetadata, Connector, ConnectorMetadata,
    ConnectorPageSource, ConnectorSplitManager, ConnectorTableHandle,
    ColumnStatistics, SchemaTableName, Split, TableMetadata, TableStatistics,
    pad_to_capacity, split_range)
from trino_tpu.page import Column, Dictionary, Page

_D12_2 = T.DecimalType(12, 2)

SCHEMAS = {
    "tiny": 0.01, "sf1": 1.0, "sf10": 10.0, "sf100": 100.0,
    "sf300": 300.0, "sf1000": 1000.0,
}

# table -> (columns, base row count at sf1); row counts per TPC-H spec 4.2.5
TABLES: Dict[str, tuple] = {
    "region": ((("r_regionkey", T.BIGINT), ("r_name", T.VarcharType(25)),
                ("r_comment", T.VarcharType(152))), None),
    "nation": ((("n_nationkey", T.BIGINT), ("n_name", T.VarcharType(25)),
                ("n_regionkey", T.BIGINT), ("n_comment", T.VarcharType(152))),
               None),
    "supplier": ((("s_suppkey", T.BIGINT), ("s_name", T.VarcharType(25)),
                  ("s_address", T.VarcharType(40)), ("s_nationkey", T.BIGINT),
                  ("s_phone", T.VarcharType(15)), ("s_acctbal", _D12_2),
                  ("s_comment", T.VarcharType(101))), 10_000),
    "customer": ((("c_custkey", T.BIGINT), ("c_name", T.VarcharType(25)),
                  ("c_address", T.VarcharType(40)), ("c_nationkey", T.BIGINT),
                  ("c_phone", T.VarcharType(15)), ("c_acctbal", _D12_2),
                  ("c_mktsegment", T.VarcharType(10)),
                  ("c_comment", T.VarcharType(117))), 150_000),
    "part": ((("p_partkey", T.BIGINT), ("p_name", T.VarcharType(55)),
              ("p_mfgr", T.VarcharType(25)), ("p_brand", T.VarcharType(10)),
              ("p_type", T.VarcharType(25)), ("p_size", T.INTEGER),
              ("p_container", T.VarcharType(10)), ("p_retailprice", _D12_2),
              ("p_comment", T.VarcharType(23))), 200_000),
    "partsupp": ((("ps_partkey", T.BIGINT), ("ps_suppkey", T.BIGINT),
                  ("ps_availqty", T.INTEGER), ("ps_supplycost", _D12_2),
                  ("ps_comment", T.VarcharType(199))), 800_000),
    "orders": ((("o_orderkey", T.BIGINT), ("o_custkey", T.BIGINT),
                ("o_orderstatus", T.VarcharType(1)), ("o_totalprice", _D12_2),
                ("o_orderdate", T.DATE),
                ("o_orderpriority", T.VarcharType(15)),
                ("o_clerk", T.VarcharType(15)), ("o_shippriority", T.INTEGER),
                ("o_comment", T.VarcharType(79))), 1_500_000),
    "lineitem": ((("l_orderkey", T.BIGINT), ("l_partkey", T.BIGINT),
                  ("l_suppkey", T.BIGINT), ("l_linenumber", T.INTEGER),
                  ("l_quantity", _D12_2), ("l_extendedprice", _D12_2),
                  ("l_discount", _D12_2), ("l_tax", _D12_2),
                  ("l_returnflag", T.VarcharType(1)),
                  ("l_linestatus", T.VarcharType(1)), ("l_shipdate", T.DATE),
                  ("l_commitdate", T.DATE), ("l_receiptdate", T.DATE),
                  ("l_shipinstruct", T.VarcharType(25)),
                  ("l_shipmode", T.VarcharType(10)),
                  ("l_comment", T.VarcharType(44))), None),  # ~4x orders
}


def table_row_count(table: str, sf: float) -> int:
    return G.row_count(table, sf)


def _column_ndv(table: str, name: str, sf: float, rows: float) -> float:
    """Real distinct counts (cost/StatsCalculator parity): FK columns get
    their DOMAIN size, not the table's row count — the round-4 q9
    join-order regression traced to l_partkey claiming 600M NDV."""
    fk_domain = {
        "l_partkey": "part", "ps_partkey": "part",
        "l_suppkey": "supplier", "ps_suppkey": "supplier",
        "l_orderkey": "orders",
    }
    if name in fk_domain:
        return float(G.row_count(fk_domain[name], sf))
    if name == "o_custkey":
        # spec: a third of customers place no orders
        return float(G.row_count("customer", sf)) * 2 / 3
    if name in ("c_nationkey", "s_nationkey", "n_nationkey"):
        return 25.0
    if name in ("n_regionkey", "r_regionkey"):
        return 5.0
    if G.string_kind(table, name) == "pooled":
        return float(min(rows, len(G.pool_values(table, name, sf))))
    if name.endswith("date"):
        return float(min(rows, 2500.0))   # ~7 years of days
    if name.endswith("key"):
        return rows                        # primary keys
    if name in ("l_quantity", "l_linenumber", "p_size", "l_discount",
                "l_tax", "o_shippriority"):
        return float(min(rows, 50.0))
    return float(min(rows, max(rows / 4, 1000.0)))


def _host_chunk(table: str, sf: float, column: str, start: int,
                end: int) -> np.ndarray:
    """Object strings or numerics for a row range (oracle / CTAS path)."""
    if G.string_kind(table, column) is not None:
        return G.object_chunk(table, sf, column, start, end)
    return G.numeric_chunk(table, sf, column, start, end)


def get_table(table: str, sf: float) -> Dict[str, np.ndarray]:
    """Full host arrays for one table (oracle loading; small sf only —
    large-sf scans go through the chunked code path instead)."""
    n = G.row_count(table, sf)
    return {name: _host_chunk(table, sf, name, 0, n)
            for name, _ in TABLES[table][0]}


_DICT_CACHE: Dict[tuple, Dictionary] = {}


def table_dictionary(table: str, sf: float, column: str) -> Dictionary:
    """Shared per-(table, sf, column) dictionary so every page of a scan uses
    one pool (stable codes across splits; one trace per table). Pooled
    columns build from their fixed pool without materializing the column;
    formatted (per-row unique) columns materialize once on first use."""
    key = (table, round(sf * 1000), column)
    if key not in _DICT_CACHE:
        if G.string_kind(table, column) == "pooled":
            _DICT_CACHE[key] = Dictionary(
                G.pool_values(table, column, sf))
        else:
            n = G.row_count(table, sf)
            data = G.object_chunk(table, sf, column, 0, n)
            _DICT_CACHE[key] = Dictionary.build(data)[0]
    return _DICT_CACHE[key]


class TpchMetadata(ConnectorMetadata):
    """plugin/trino-tpch TpchMetadata.java analog."""

    def list_schemas(self) -> List[str]:
        return sorted(SCHEMAS)

    def list_tables(self, schema: Optional[str] = None) -> List[SchemaTableName]:
        schemas = [schema] if schema else sorted(SCHEMAS)
        return [SchemaTableName(s, t) for s in schemas for t in sorted(TABLES)]

    def get_table_handle(self, name: SchemaTableName) -> Optional[ConnectorTableHandle]:
        if name.schema in SCHEMAS and name.table in TABLES:
            return ConnectorTableHandle(name)
        return None

    def get_table_metadata(self, handle: ConnectorTableHandle) -> TableMetadata:
        cols = tuple(ColumnMetadata(n, t)
                     for n, t in TABLES[handle.name.table][0])
        return TableMetadata(handle.name, cols)

    def get_table_statistics(self, handle: ConnectorTableHandle) -> TableStatistics:
        sf = SCHEMAS[handle.name.schema]
        rows = float(table_row_count(handle.name.table, sf))
        cols: Dict[str, ColumnStatistics] = {}
        for name, typ in TABLES[handle.name.table][0]:
            cols[name] = ColumnStatistics(
                null_fraction=0.0,
                distinct_count=_column_ndv(handle.name.table, name, sf,
                                           rows))
        return TableStatistics(rows, cols)

    # date-derived status columns are heavily skewed (e.g. ~2/3 of orders
    # are fulfilled 'F'), so pool-uniform estimation would mislead
    _SKEWED_POOLED = {"o_orderstatus", "l_returnflag", "l_linestatus"}

    def estimate_like_selectivity(self, handle, column, pattern,
                                  escape=None):
        """Exact match fraction over the column's dictionary pool — valid
        because every non-skewed pooled column draws codes UNIFORMLY from
        its pool (tpch_gen `_ui` streams)."""
        table = handle.name.table
        if G.string_kind(table, column) != "pooled" \
                or column in self._SKEWED_POOLED:
            return None
        import re as _re
        from trino_tpu.expr.functions import like_pattern_to_regex
        values = G.pool_values(table, column, SCHEMAS[handle.name.schema])
        if len(values) == 0:
            return None
        rx = _re.compile(like_pattern_to_regex(pattern, escape), _re.DOTALL)
        hits = sum(1 for v in values if rx.match(v))
        return hits / len(values)

    def apply_filter(self, handle, constraint):
        # accept the whole domain for split pruning; engine re-applies row-wise
        merged = handle.constraint.intersect(constraint)
        return (ConnectorTableHandle(handle.name, merged, handle.limit),
                constraint)

    def apply_limit(self, handle, limit):
        if handle.limit is not None and handle.limit <= limit:
            return None
        return ConnectorTableHandle(handle.name, handle.constraint, limit)


class TpchSplitManager(ConnectorSplitManager):
    def get_splits(self, handle: ConnectorTableHandle,
                   target_splits: int = 1) -> List[Split]:
        sf = SCHEMAS[handle.name.schema]
        rows = table_row_count(handle.name.table, sf)
        parts = max(1, min(target_splits, math.ceil(rows / 4096)))
        return [Split(handle, p, parts, host=p) for p in range(parts)]


import collections
import os
import threading

# device-side generation (tpch_dev): default ON; set =0 to force the host
# numpy path (debugging / byte-equivalence comparisons)
_DEVICE_GEN = os.environ.get("TRINO_TPU_DEVICE_GEN", "1") != "0"

# one lock for both LRU caches: the server's executor pool scans
# concurrently, and the byte-accounting (USED counters vs OrderedDict)
# must not interleave. Generation under the lock serializes a cold miss;
# warm hits are a dict probe.
_CACHE_LOCK = threading.RLock()

# host-side generated-chunk LRU: at SF100 the working set (~29GB for q9's
# seven lineitem/orders columns) exceeds the DEVICE cache budget, and
# regenerating hash streams for 600M rows costs minutes per run — the host
# has 125GB RAM, so warm benchmark runs keep the numpy chunks resident
_HOST_CHUNK_CACHE: "collections.OrderedDict[tuple, np.ndarray]" = \
    collections.OrderedDict()
_HOST_CHUNK_CACHE_BYTES = int(os.environ.get(
    "TRINO_TPU_HOST_CHUNK_CACHE_BYTES", 48 << 30))
_HOST_CHUNK_CACHE_USED = 0


def _host_cached(key: tuple, build) -> np.ndarray:
    global _HOST_CHUNK_CACHE_USED
    with _CACHE_LOCK:
        arr = _HOST_CHUNK_CACHE.get(key)
        if arr is not None:
            _HOST_CHUNK_CACHE.move_to_end(key)
            return arr
    # build OUTSIDE the lock: a cold SF100 chunk generation takes minutes
    # and must not stall concurrent queries' warm cache hits (two racers
    # may both build; check-then-insert keeps the accounting exact)
    arr = build()
    nbytes = arr.nbytes
    with _CACHE_LOCK:
        if nbytes <= _HOST_CHUNK_CACHE_BYTES \
                and key not in _HOST_CHUNK_CACHE:
            while (_HOST_CHUNK_CACHE_USED + nbytes > _HOST_CHUNK_CACHE_BYTES
                   and _HOST_CHUNK_CACHE):
                _, evicted = _HOST_CHUNK_CACHE.popitem(last=False)
                _HOST_CHUNK_CACHE_USED -= evicted.nbytes
            _HOST_CHUNK_CACHE[key] = arr
            _HOST_CHUNK_CACHE_USED += nbytes
    return arr


_DEVICE_COL_CACHE: "collections.OrderedDict[tuple, Column]" = \
    collections.OrderedDict()
# LRU byte budget for staged table columns (HBM residency is finite;
# unbounded growth was flagged in round 2). Override for small chips.
_DEVICE_COL_CACHE_BYTES = int(os.environ.get(
    "TRINO_TPU_SCAN_CACHE_BYTES", 4 << 30))
_DEVICE_COL_CACHE_USED = 0


def set_device_cache_budget(nbytes: int) -> None:
    """Adjust the staged-column LRU budget at runtime (bench shrinks it
    before SF100 rungs so join state owns the HBM, evicting as needed)."""
    global _DEVICE_COL_CACHE_BYTES, _DEVICE_COL_CACHE_USED
    with _CACHE_LOCK:
        _DEVICE_COL_CACHE_BYTES = int(nbytes)
        while _DEVICE_COL_CACHE_USED > _DEVICE_COL_CACHE_BYTES \
                and _DEVICE_COL_CACHE:
            _, evicted = _DEVICE_COL_CACHE.popitem(last=False)
            _DEVICE_COL_CACHE_USED -= evicted.nbytes


def _staged_column(table: str, sf: float, name: str, typ: T.Type,
                   off: int, hi: int, page_capacity: int) -> Column:
    """Generate + pad + stage one column slice to device, once per
    (table, sf, column, slice, capacity), LRU-evicted under a byte budget.

    The reference streams table data from storage per query; TPC-H data here
    is immutable generator output, so re-staging identical bytes to HBM on
    every execution would only re-measure PCIe. Real-table residency analog:
    Trino's memory connector / a warmed OS page cache."""
    global _DEVICE_COL_CACHE_USED
    key = (table, round(sf * 1000), name, off, hi, page_capacity)
    with _CACHE_LOCK:
        col = _DEVICE_COL_CACHE.get(key)
        if col is not None:
            _DEVICE_COL_CACHE.move_to_end(key)
            return col
    hkey = (table, round(sf * 1000), name, off, hi)
    if _DEVICE_GEN and tpch_dev.supported(table, name):
        # generate ON the device: same hash-stream expressions jit'd via
        # jnp (tpch_dev docstring) — no host hashing, no column transfer
        import jax.numpy as jnp
        values = tpch_dev.generate(table, sf, name, off, hi, page_capacity)
        if T.is_string(typ):
            col = Column(values, None, typ,
                         table_dictionary(table, sf, name))
        else:
            col = Column(values.astype(T.to_numpy_dtype(typ)), None, typ)
    elif T.is_string(typ):
        d = table_dictionary(table, sf, name)
        if G.string_kind(table, name) == "pooled":
            codes = _host_cached(
                hkey, lambda: G.codes_chunk(table, sf, name, off, hi))
        else:
            codes = _host_cached(
                hkey, lambda: d.encode(
                    G.object_chunk(table, sf, name, off, hi)))
        col = Column.from_numpy(pad_to_capacity(codes, page_capacity, 0),
                                typ, dictionary=d)
    else:
        arr = pad_to_capacity(
            _host_cached(hkey, lambda: np.asarray(
                G.numeric_chunk(table, sf, name, off, hi),
                T.to_numpy_dtype(typ))), page_capacity, 0)
        col = Column.from_numpy(arr, typ)
    nbytes = col.nbytes
    with _CACHE_LOCK:
        if nbytes > _DEVICE_COL_CACHE_BYTES:
            return col   # larger than the whole budget: never cache
        if key not in _DEVICE_COL_CACHE:
            while (_DEVICE_COL_CACHE_USED + nbytes
                   > _DEVICE_COL_CACHE_BYTES and _DEVICE_COL_CACHE):
                _, evicted = _DEVICE_COL_CACHE.popitem(last=False)
                _DEVICE_COL_CACHE_USED -= evicted.nbytes
            _DEVICE_COL_CACHE[key] = col
            _DEVICE_COL_CACHE_USED += nbytes
    return col


class TpchPageSource(ConnectorPageSource):
    def pages(self, split: Split, columns: Sequence[ColumnHandle],
              page_capacity: int) -> Iterator[Page]:
        handle = split.table
        table = handle.name.table
        sf = SCHEMAS[handle.name.schema]
        total = table_row_count(table, sf)
        start, end = split_range(total, split.part, split.total_parts)
        if handle.limit is not None:
            end = min(end, start + handle.limit)
        for off in range(start, end, page_capacity):
            hi = min(off + page_capacity, end)
            n = hi - off
            cols = [_staged_column(table, sf, ch.name, ch.type, off, hi,
                                   page_capacity) for ch in columns]
            yield Page(tuple(cols), n)


def create_connector() -> Connector:
    return Connector("tpch", TpchMetadata(), TpchSplitManager(),
                     TpchPageSource())
